"""ModelManager hot-swap: zero-downtime swaps under concurrent load,
rollback on warmup failure, rollback on breaker-open within probation,
canary lifecycle (serving/manager.py). All on CPU via the seeded
FaultInjector and fake clocks — ISSUE 4 acceptance criteria."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.core.resilience import CircuitBreaker, FaultInjector
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.obs import MetricsRegistry
from deeplearning4j_tpu.parallel.inference import FORWARD_SITE
from deeplearning4j_tpu.serving import (
    WARMUP_SITE,
    ModelManager,
    ModelStore,
    SwapError,
    VersionNotFoundError,
)


def _model(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture
def store(tmp_path):
    s = ModelStore(str(tmp_path / "registry"))
    s.publish("m", _model(1))
    s.publish("m", _model(2))
    return s


def _swap_count(registry, outcome):
    fam = registry.get("dl4j_tpu_serving_swap_total")
    return fam.labels("m", outcome).value if fam else 0.0


def test_hot_swap_under_concurrent_load_zero_failures(store):
    """The acceptance-criterion test: a client thread pool hammers the
    engine while versions swap back and forth; every request succeeds
    and every response is exactly one of the two versions' outputs."""
    reg = MetricsRegistry()
    mgr = ModelManager(store, "m", version=1, registry=reg, workers=2,
                       batch_limit=4, probation_seconds=0.0)
    x = np.random.RandomState(3).randn(1, 4).astype(np.float32)
    m1, _ = store.load("m", 1)
    m2, _ = store.load("m", 2)
    # tolerance, not bytes: the engine's bucketed/padded batch forward is
    # not bit-identical to a single-row model.output
    expect = [np.asarray(m1.output(x), np.float32),
              np.asarray(m2.output(x), np.float32)]

    n_clients, n_swaps = 6, 4
    failures = []
    mismatches = []
    swapping = threading.Event()
    swapping.set()

    def client():
        # hammer until every swap has happened (≥1 request guaranteed)
        done_once = False
        while swapping.is_set() or not done_once:
            done_once = True
            try:
                out = np.asarray(mgr.output(x, timeout=30.0), np.float32)
                if not any(np.allclose(out, e, atol=1e-4) for e in expect):
                    mismatches.append(out)
            except Exception as e:  # any failure breaks the criterion
                failures.append(e)

    threads = [threading.Thread(target=client) for _ in range(n_clients)]
    for t in threads:
        t.start()

    def swapper():
        try:
            v = 2
            for _ in range(n_swaps):
                mgr.deploy(v)
                v = 1 if v == 2 else 2
                time.sleep(0.05)
        finally:
            swapping.clear()

    sw = threading.Thread(target=swapper)
    sw.start()
    sw.join(timeout=300)
    for t in threads:
        t.join(timeout=120)
    try:
        assert failures == [], f"requests failed during swap: {failures[:3]}"
        assert mismatches == [], "a response matched neither version"
        s = mgr.stats()
        assert s["completed"] >= n_clients  # every client got answers
        assert s["failed"] == 0 and s["shed"] == 0 and s["timed_out"] == 0
        assert _swap_count(reg, "completed") == n_swaps
    finally:
        mgr.shutdown(drain=False)


def test_warmup_failure_keeps_prior_version_live(store):
    reg = MetricsRegistry()
    inj = FaultInjector()
    mgr = ModelManager(store, "m", version=1, registry=reg,
                       fault_injector=inj, batch_limit=4)
    x = np.ones((2, 4), np.float32)
    before = np.asarray(mgr.output(x))  # also seeds last_input_shape
    inj.inject_error(WARMUP_SITE, lambda: RuntimeError("bad compile"),
                     times=1)
    with pytest.raises(SwapError, match="warmup failed"):
        mgr.deploy(2)
    try:
        assert mgr.live_version == "1"
        np.testing.assert_allclose(np.asarray(mgr.output(x)), before,
                                   atol=1e-6)
        assert _swap_count(reg, "warmup_failed") == 1
        assert _swap_count(reg, "completed") == 0
        # the store is intact: a later deploy (no fault armed) succeeds
        mgr.deploy(2)
        assert mgr.live_version == "2"
    finally:
        mgr.shutdown(drain=False)


def test_breaker_open_in_probation_rolls_back_automatically(store):
    clk = [0.0]
    reg = MetricsRegistry()
    inj = FaultInjector()
    mgr = ModelManager(
        store, "m", version=1, registry=reg, fault_injector=inj,
        workers=1, batch_limit=4, probation_seconds=60.0,
        clock=lambda: clk[0],
        breaker_factory=lambda: CircuitBreaker(
            failure_threshold=1.0, min_calls=2, window=4,
            open_timeout=60.0, clock=lambda: clk[0]))
    x = np.ones((2, 4), np.float32)
    v1_out = np.asarray(mgr.output(x))
    mgr.deploy(2)
    assert mgr.live_version == "2"
    inj.inject_error(FORWARD_SITE, lambda: RuntimeError("poisoned"), times=2)
    for _ in range(2):
        with pytest.raises(RuntimeError):
            mgr.output(x, timeout=10.0)
    try:
        for _ in range(500):  # rollback fires from the worker thread
            if mgr.live_version == "1":
                break
            time.sleep(0.01)
        assert mgr.live_version == "1"
        assert _swap_count(reg, "rolled_back") == 1
        np.testing.assert_allclose(np.asarray(mgr.output(x)), v1_out,
                                   atol=1e-6)
        assert mgr.describe()["circuit"] == "closed"
    finally:
        mgr.shutdown(drain=False)


def test_breaker_open_after_probation_does_not_roll_back(store):
    clk = [0.0]
    inj = FaultInjector()
    mgr = ModelManager(
        store, "m", version=1, fault_injector=inj, registry=MetricsRegistry(),
        workers=1, batch_limit=4, probation_seconds=60.0,
        clock=lambda: clk[0],
        breaker_factory=lambda: CircuitBreaker(
            failure_threshold=1.0, min_calls=2, window=4,
            open_timeout=60.0, clock=lambda: clk[0]))
    x = np.ones((2, 4), np.float32)
    mgr.output(x)
    mgr.deploy(2)
    clk[0] += 61.0  # probation window elapses
    inj.inject_error(FORWARD_SITE, lambda: RuntimeError("poisoned"), times=2)
    for _ in range(2):
        with pytest.raises(RuntimeError):
            mgr.output(x, timeout=10.0)
    try:
        time.sleep(0.1)
        assert mgr.live_version == "2"  # breaker open, but no rollback
    finally:
        mgr.shutdown(drain=False)


def test_manual_rollback_and_confirm(store):
    mgr = ModelManager(store, "m", version=1, registry=MetricsRegistry(),
                       batch_limit=4)
    with pytest.raises(SwapError):
        mgr.rollback()  # nothing resident to roll back to
    x = np.ones((1, 4), np.float32)
    mgr.output(x)
    mgr.deploy(2)
    assert mgr.previous_version == "1"
    mgr.confirm()
    assert mgr.describe()["probation_remaining"] == 0.0
    entry = mgr.rollback()
    try:
        assert (entry.version, mgr.live_version) == (1, "1")
        assert mgr.previous_version is None
    finally:
        mgr.shutdown(drain=False)


def test_deploy_same_version_is_noop(store):
    reg = MetricsRegistry()
    mgr = ModelManager(store, "m", version=2, registry=reg, batch_limit=4)
    try:
        entry = mgr.deploy(2)
        assert entry.version == 2
        assert _swap_count(reg, "completed") == 0
    finally:
        mgr.shutdown(drain=False)


def test_per_version_request_counters_and_pinning(store):
    reg = MetricsRegistry()
    mgr = ModelManager(store, "m", version=1, registry=reg, batch_limit=4)
    x = np.ones((1, 4), np.float32)
    try:
        mgr.output(x)
        mgr.deploy(2)
        mgr.output(x)
        mgr.output(x)
        fam = reg.get("dl4j_tpu_serving_model_requests_total")
        assert fam.labels("m-live", "1").value == 1
        assert fam.labels("m-live", "2").value == 2
        # pinning: live version answers, absent version is a loud miss
        fut, served = mgr.submit(x, version=2)
        fut.result()
        assert served == "2"
        with pytest.raises(VersionNotFoundError):
            mgr.submit(x, version=9)
        assert mgr.stats()["model_version"] == "2"
    finally:
        mgr.shutdown(drain=False)


def test_canary_rollout_and_promotion(store):
    reg = MetricsRegistry()
    mgr = ModelManager(store, "m", version=1, registry=reg, batch_limit=4,
                       probation_seconds=60.0)
    x = np.ones((1, 4), np.float32)
    try:
        mgr.output(x)
        mgr.start_canary(2, weight=0.5)
        desc = mgr.describe()
        assert desc["canary"] == {"version": "2", "weight": 0.5,
                                  "shadow": False, "circuit": "closed",
                                  "quantized_layers": 0}
        served = set()
        for i in range(40):
            fut, v = mgr.submit(x, key=f"user-{i}")
            fut.result()
            served.add(v)
        assert served == {"1", "2"}  # both sides of the split saw traffic
        # the same key always lands on the same side
        v_first = mgr.submit(x, key="sticky")[1]
        for _ in range(5):
            assert mgr.submit(x, key="sticky")[1] == v_first
        mgr.promote_canary()
        assert mgr.live_version == "2"
        assert mgr.canary_version is None
        assert _swap_count(reg, "canary_promoted") == 1
    finally:
        mgr.shutdown(drain=False)


def test_canary_breaker_open_stops_canary_not_live(store):
    clk = [0.0]
    reg = MetricsRegistry()
    inj = FaultInjector()
    mgr = ModelManager(
        store, "m", version=1, registry=reg, fault_injector=inj,
        workers=1, batch_limit=4, probation_seconds=60.0,
        clock=lambda: clk[0],
        breaker_factory=lambda: CircuitBreaker(
            failure_threshold=1.0, min_calls=2, window=4,
            open_timeout=60.0, clock=lambda: clk[0]))
    x = np.ones((1, 4), np.float32)
    try:
        mgr.output(x)
        mgr.start_canary(2, weight=1.0)  # all traffic to the canary
        inj.inject_error(FORWARD_SITE, lambda: RuntimeError("poisoned"),
                         times=2)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                mgr.output(x, timeout=10.0)
        for _ in range(500):  # reaper tears the canary down asynchronously
            if _swap_count(reg, "rolled_back") >= 1:
                break
            time.sleep(0.01)
        assert mgr.canary_version is None  # canary torn down...
        assert mgr.live_version == "1"     # ...live untouched
        assert _swap_count(reg, "rolled_back") == 1
        np.testing.assert_allclose(
            np.asarray(mgr.output(x)),
            np.asarray(store.load("m", 1)[0].output(x)), atol=1e-6)
    finally:
        mgr.shutdown(drain=False)


def test_shadow_mode_mirrors_without_affecting_responses(store):
    reg = MetricsRegistry()
    mgr = ModelManager(store, "m", version=1, registry=reg, batch_limit=4)
    x = np.ones((1, 4), np.float32)
    try:
        v1_out = np.asarray(mgr.output(x))
        mgr.start_canary(2, shadow=True)
        for i in range(5):
            fut, v = mgr.submit(x, key=f"k{i}")
            assert v == "1"  # responses come from live only
            np.testing.assert_allclose(np.asarray(fut.result()), v1_out,
                                       atol=1e-6)
        for _ in range(500):  # mirrored submissions settle asynchronously
            if mgr._canary_engine.stats()["completed"] >= 5:
                break
            time.sleep(0.01)
        assert mgr._canary_engine.stats()["completed"] == 5
        fam = reg.get("dl4j_tpu_serving_routes_total")
        assert fam.labels("m", "shadow").value == 5
        assert fam.labels("m", "primary").value == 5
        assert fam.labels("m", "canary").value == 0
    finally:
        mgr.shutdown(drain=False)


def test_manager_gc_protects_resident_versions(store):
    for seed in (3, 4, 5):
        store.publish("m", _model(seed))  # now v1..v5
    mgr = ModelManager(store, "m", version=4, registry=MetricsRegistry(),
                       batch_limit=4)
    x = np.ones((1, 4), np.float32)
    try:
        mgr.output(x)
        mgr.deploy(5)  # live=5, previous=4
        removed = mgr.gc(keep_last=1)
        assert removed == {"m": [1, 2, 3]}
        assert [v.version for v in store.versions("m")] == [4, 5]
    finally:
        mgr.shutdown(drain=False)


def test_gc_never_collects_running_canary(store):
    """ISSUE 13 satellite regression: the manager reports its CANARY
    version in ``in_use`` alongside live/previous, so a long-running
    canary can never be collected mid-experiment — and the protection
    lifts the moment the canary stops."""
    for seed in (3, 4, 5):
        store.publish("m", _model(seed))  # now v1..v5
    mgr = ModelManager(store, "m", version=5, registry=MetricsRegistry(),
                      batch_limit=4, probation_seconds=3600.0)
    x = np.ones((1, 4), np.float32)
    try:
        mgr.output(x)
        mgr.start_canary(2, weight=1.0)  # canary on an OLD version
        assert mgr.resident_versions() == {2, 5}
        removed = mgr.gc(keep_last=1)
        # v2 (canary) and v5 (live + latest) survive; everything else goes
        assert removed == {"m": [1, 3, 4]}
        assert [v.version for v in store.versions("m")] == [2, 5]
        # the canary still serves from its (protected) artifact
        fut, served = mgr.submit(x, key="canary-bound")
        fut.result(timeout=10)
        assert served == "2"
        # protection is tied to the canary's lifetime, not permanent
        mgr.stop_canary()
        assert mgr.resident_versions() == {5}
        assert mgr.gc(keep_last=1) == {"m": [2]}
        assert [v.version for v in store.versions("m")] == [5]
    finally:
        mgr.shutdown(drain=False)


def test_gc_never_collects_parked_versions(store):
    """ISSUE 19 satellite regression: a PARKED manager (weights paged
    out by the multiplexer) keeps reporting its live/previous/canary
    versions in ``resident_versions()``, so GC can never delete the
    artifact a later page-in needs — the paged-out analogue of the
    canary protection above."""
    for seed in (3, 4, 5):
        store.publish("m", _model(seed))  # now v1..v5
    mgr = ModelManager(store, "m", version=4, registry=MetricsRegistry(),
                       batch_limit=4, probation_seconds=3600.0)
    x = np.ones((1, 4), np.float32)
    try:
        before = np.asarray(mgr.output(x))
        mgr.deploy(5)           # live=5, previous=4
        mgr.start_canary(2, weight=0.5)
        assert mgr.resident_versions() == {2, 4, 5}
        mgr.park()
        # paged out, but the page-in still needs all three artifacts
        assert mgr.resident_versions() == {2, 4, 5}
        removed = mgr.gc(keep_last=1)
        assert removed == {"m": [1, 3]}
        assert [v.version for v in store.versions("m")] == [2, 4, 5]
        # and the page-in actually works off the protected artifacts —
        # live version, canary spec and all
        mgr.unpark()
        assert mgr.live_version == "5"
        assert mgr.canary_version == "2"
        assert np.asarray(mgr.output(x)).shape == before.shape
    finally:
        mgr.shutdown(drain=False)
