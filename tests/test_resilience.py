"""core/resilience.py unit tests — every state machine driven by a fake
clock and the seeded FaultInjector, no wall-clock sleeps."""

import pytest

from deeplearning4j_tpu.core.resilience import (
    AdmissionController,
    AdmissionRejectedError,
    CircuitBreaker,
    CircuitOpenError,
    CircuitState,
    Deadline,
    DeadlineExceededError,
    FaultInjector,
    RetryPolicy,
    get_fault_injector,
    set_fault_injector,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt

    def sleep(self, dt: float) -> None:  # a sleep that only moves the clock
        self.t += dt


# ---------------------------------------------------------------- Deadline
class TestDeadline:
    def test_remaining_and_expiry(self):
        clk = FakeClock()
        dl = Deadline.after(2.0, clock=clk)
        assert dl.remaining() == pytest.approx(2.0)
        assert not dl.expired()
        clk.advance(2.5)
        assert dl.expired()
        assert dl.remaining() == pytest.approx(-0.5)
        with pytest.raises(DeadlineExceededError):
            dl.check("probe")

    def test_unbounded(self):
        dl = Deadline.never()
        assert dl.remaining() is None
        assert not dl.expired()
        dl.check()  # never raises

    def test_deadline_exceeded_is_timeout(self):
        # ParallelInference contract: expired requests surface TimeoutError
        assert issubclass(DeadlineExceededError, TimeoutError)


# ------------------------------------------------------------- RetryPolicy
class TestRetryPolicy:
    def test_backoff_exponential_and_capped(self):
        p = RetryPolicy(initial_backoff=0.1, multiplier=2.0, max_backoff=0.5,
                        jitter=0.0)
        assert [p.backoff(i) for i in range(4)] == \
            pytest.approx([0.1, 0.2, 0.4, 0.5])

    def test_seeded_jitter_deterministic_and_bounded(self):
        a = [RetryPolicy(jitter=0.5, seed=7).backoff(i) for i in range(5)]
        b = [RetryPolicy(jitter=0.5, seed=7).backoff(i) for i in range(5)]
        assert a == b  # same seed -> same delays
        for i, d in enumerate(a):
            base = min(10.0, 0.1 * 2.0 ** i)
            assert base * 0.5 <= d <= base

    def test_execute_retries_then_succeeds(self):
        clk = FakeClock()
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("down")
            return "ok"

        p = RetryPolicy(max_retries=3, initial_backoff=0.1, jitter=0.0)
        assert p.execute(flaky, retry_on=(ConnectionError,),
                         sleep=clk.sleep) == "ok"
        assert len(calls) == 3
        assert clk.t == pytest.approx(0.1 + 0.2)

    def test_execute_exhausts_and_reraises(self):
        p = RetryPolicy(max_retries=2, initial_backoff=0.01, jitter=0.0)
        calls = []

        def always():
            calls.append(1)
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            p.execute(always, retry_on=(ConnectionError,),
                      sleep=FakeClock().sleep)
        assert len(calls) == 3  # 1 + 2 retries

    def test_execute_never_retries_unlisted(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("malformed")

        with pytest.raises(ValueError):
            RetryPolicy().execute(bad, retry_on=(ConnectionError,),
                                  sleep=FakeClock().sleep)
        assert len(calls) == 1

    def test_execute_respects_deadline(self):
        clk = FakeClock()
        p = RetryPolicy(max_retries=5, initial_backoff=2.0, jitter=0.0)

        def always():
            raise ConnectionError("down")

        # 1s budget, 2s backoff: the retry cannot fit -> immediate re-raise
        with pytest.raises(ConnectionError):
            p.execute(always, retry_on=(ConnectionError,),
                      deadline=Deadline.after(1.0, clock=clk),
                      sleep=clk.sleep)
        assert clk.t == 0.0  # never slept

    def test_execute_honors_retry_after_hint(self):
        clk = FakeClock()
        delays = []

        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise CircuitOpenError(retry_after=3.0)
            return "ok"

        p = RetryPolicy(max_retries=2, initial_backoff=0.1, jitter=0.0)
        p.execute(flaky, retry_on=(CircuitOpenError,), sleep=clk.sleep,
                  on_retry=lambda a, e, d: delays.append(d))
        assert delays == [3.0]  # server hint overrides the smaller backoff


# ---------------------------------------------------------- CircuitBreaker
def _breaker(clk, **kw):
    kw.setdefault("failure_threshold", 0.5)
    kw.setdefault("min_calls", 4)
    kw.setdefault("open_timeout", 10.0)
    return CircuitBreaker(clock=clk, **kw)


class TestCircuitBreaker:
    def test_stays_closed_below_min_calls(self):
        cb = _breaker(FakeClock())
        for _ in range(3):
            cb.record_failure()
        assert cb.state is CircuitState.CLOSED
        assert cb.allow()

    def test_opens_at_failure_rate(self):
        cb = _breaker(FakeClock())
        cb.record_success()
        cb.record_success()
        cb.record_failure()
        assert cb.state is CircuitState.CLOSED
        cb.record_failure()  # 2/4 = threshold
        assert cb.state is CircuitState.OPEN
        assert not cb.allow()
        with pytest.raises(CircuitOpenError) as ei:
            cb.check()
        assert 0.0 < ei.value.retry_after <= 10.0

    def test_half_open_probe_then_close(self):
        clk = FakeClock()
        cb = _breaker(clk)
        for _ in range(4):
            cb.record_failure()
        assert cb.state is CircuitState.OPEN
        clk.advance(10.0)
        assert cb.state is CircuitState.HALF_OPEN
        assert cb.allow()        # the single probe
        assert not cb.allow()    # concurrent second call rejected
        cb.record_success()
        assert cb.state is CircuitState.CLOSED
        # the window was reset: one failure must not instantly re-trip
        cb.record_failure()
        assert cb.state is CircuitState.CLOSED

    def test_half_open_probe_failure_reopens(self):
        clk = FakeClock()
        cb = _breaker(clk)
        for _ in range(4):
            cb.record_failure()
        clk.advance(10.0)
        assert cb.allow()
        cb.record_failure()
        assert cb.state is CircuitState.OPEN
        # fresh timeout: not half-open again until another full open_timeout
        clk.advance(5.0)
        assert cb.state is CircuitState.OPEN
        clk.advance(5.0)
        assert cb.state is CircuitState.HALF_OPEN

    def test_call_wrapper_records(self):
        cb = _breaker(FakeClock(), min_calls=2, failure_threshold=1.0)
        assert cb.call(lambda: 42) == 42
        with pytest.raises(RuntimeError):
            cb.call(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        assert cb.state is CircuitState.CLOSED  # 1/2 failures < 1.0

    # ---- ISSUE 12 satellite: half-open under N concurrent probes ------
    def _opened(self, clk):
        cb = _breaker(clk)
        for _ in range(4):
            cb.record_failure()
        assert cb.state is CircuitState.OPEN
        clk.advance(10.0)  # timeout elapsed: next allow() is the trial
        return cb

    def _concurrent_allow(self, cb, n=16):
        """n threads race allow() through a barrier; returns the list of
        verdicts."""
        import threading

        barrier = threading.Barrier(n)
        results = []
        lock = threading.Lock()

        def worker():
            barrier.wait()
            ok = cb.allow()
            with lock:
                results.append(ok)

        threads = [threading.Thread(target=worker) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert len(results) == n
        return results

    def test_half_open_concurrent_probes_exactly_one_trial(self):
        cb = self._opened(FakeClock())
        results = self._concurrent_allow(cb)
        assert sum(results) == 1, \
            f"exactly one trial slot, got {sum(results)} (thundering herd)"
        # while the trial is in flight, later callers keep being rejected
        assert not cb.allow()
        assert cb.state is CircuitState.HALF_OPEN

    def test_half_open_failed_trial_reopens_under_concurrency(self):
        clk = FakeClock()
        cb = self._opened(clk)
        assert sum(self._concurrent_allow(cb)) == 1
        cb.record_failure()  # the one trial fails
        assert cb.state is CircuitState.OPEN
        # a fresh full timeout gates the NEXT single trial
        assert sum(self._concurrent_allow(cb)) == 0
        clk.advance(10.0)
        assert sum(self._concurrent_allow(cb)) == 1

    def test_half_open_successful_trial_closes_for_everyone(self):
        cb = self._opened(FakeClock())
        assert sum(self._concurrent_allow(cb)) == 1
        cb.record_success()  # the one trial succeeds
        assert cb.state is CircuitState.CLOSED
        assert all(self._concurrent_allow(cb))  # closed: no gating

    def test_half_open_max_calls_n_admits_exactly_n(self):
        clk = FakeClock()
        cb = CircuitBreaker(failure_threshold=0.5, min_calls=4, window=8,
                            open_timeout=10.0, half_open_max_calls=3,
                            clock=clk)
        for _ in range(4):
            cb.record_failure()
        clk.advance(10.0)
        assert sum(self._concurrent_allow(cb)) == 3

    def test_release_frees_half_open_slot_without_state_change(self):
        """A call that ends with neither success nor failure (caller's
        bad input, caller's deadline) must give the trial slot back —
        otherwise the breaker wedges in HALF_OPEN and no probe can ever
        close it again."""
        cb = self._opened(FakeClock())
        assert cb.allow()        # trial slot taken
        assert not cb.allow()
        cb.release()             # neutral outcome: slot freed
        assert cb.state is CircuitState.HALF_OPEN  # state untouched
        assert cb.allow()        # the NEXT probe can run
        cb.record_success()
        assert cb.state is CircuitState.CLOSED

    def test_release_is_noop_when_closed(self):
        cb = _breaker(FakeClock())
        cb.release()  # never reserved anything: harmless
        assert cb.state is CircuitState.CLOSED
        assert cb.allow()


# ----------------------------------------------------- AdmissionController
class TestAdmissionController:
    def test_pending_cap_sheds(self):
        ac = AdmissionController(max_pending=2)
        ac.admit()
        ac.admit()
        with pytest.raises(AdmissionRejectedError):
            ac.admit()
        ac.release()
        ac.admit()  # slot freed
        assert ac.stats() == {"pending": 2, "admitted": 3, "shed": 1}

    def test_token_bucket_rate_limit(self):
        clk = FakeClock()
        ac = AdmissionController(max_pending=100, rate=2.0, burst=2.0,
                                 clock=clk)
        assert ac.try_admit() and ac.try_admit()
        assert not ac.try_admit()  # bucket empty
        clk.advance(0.5)           # refills one token at 2/s
        assert ac.try_admit()
        assert not ac.try_admit()
        assert ac.retry_after() == pytest.approx(0.5)

    def test_burst_caps_refill(self):
        clk = FakeClock()
        ac = AdmissionController(max_pending=100, rate=10.0, burst=3.0,
                                 clock=clk)
        clk.advance(100.0)  # long idle must not bank unlimited tokens
        got = sum(ac.try_admit() for _ in range(10))
        assert got == 3


# ------------------------------------------------------------ FaultInjector
class TestFaultInjector:
    def test_inert_by_default(self):
        FaultInjector().fire("anywhere")  # no plan -> no-op

    def test_error_times_budget(self):
        inj = FaultInjector()
        inj.inject_error("site", lambda: RuntimeError("boom"), times=2)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                inj.fire("site")
        inj.fire("site")  # exhausted -> inert
        assert inj.fired("site") == 2

    def test_probability_seeded_deterministic(self):
        def run(seed):
            inj = FaultInjector(seed=seed)
            inj.inject_error("s", lambda: RuntimeError("x"), times=None,
                             probability=0.5)
            fired = []
            for _ in range(20):
                try:
                    inj.fire("s")
                    fired.append(0)
                except RuntimeError:
                    fired.append(1)
            return fired

        assert run(3) == run(3)           # replayable
        assert 0 < sum(run(3)) < 20       # actually probabilistic

    def test_latency_uses_injected_sleep(self):
        slept = []
        inj = FaultInjector(sleep=slept.append)
        inj.inject_latency("slow", 0.25, times=1)
        inj.fire("slow")
        inj.fire("slow")
        assert slept == [0.25]

    def test_clear_site(self):
        inj = FaultInjector()
        inj.inject_error("a", lambda: RuntimeError("x"), times=None)
        inj.clear("a")
        inj.fire("a")

    def test_global_injector_swap_and_restore(self):
        mine = FaultInjector()
        prev = set_fault_injector(mine)
        try:
            assert get_fault_injector() is mine
        finally:
            set_fault_injector(prev)
        assert get_fault_injector() is prev
