"""Tier-1 wiring for tools/check_metrics_contract.py: the /metrics scrape
contract (README.md "Observability" — exposition grammar + contract series
names) is enforced on every test run, mirroring test_serving_contract.py."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_metrics_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_metrics_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_metrics_contract.main(log=lambda m: None) == 0
