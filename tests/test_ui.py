"""Stats storage / profiling / NaN panic tests (SURVEY.md §5.1, §5.5)."""

import json
import math

import numpy as np
import pytest

from deeplearning4j_tpu.core.listeners import EvaluativeListener
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.ui import (
    FileStatsStorage,
    InMemoryStatsStorage,
    NanPanicListener,
    ProfilingListener,
    StatsListener,
)


def _model(seed=3):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=1e-2)).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    return x, y


def test_stats_listener_collects_params_grads_updates():
    model = _model()
    storage = InMemoryStatsStorage()
    model.add_listeners(StatsListener(storage, session_id="s1",
                                      update_frequency=1))
    x, y = _data()
    model.fit(x, y, epochs=5)
    recs = storage.records("s1")
    assert len(recs) == 5
    full = [r for r in recs if "params" in r]
    assert full, "no full stat records collected"
    r = full[-1]
    assert "layer_0/W" in r["params"]
    stats = r["params"]["layer_0/W"]
    assert {"mean", "std", "norm", "histogram"} <= set(stats)
    assert sum(stats["histogram"]["counts"]) == 4 * 8
    assert "gradients" in r and "layer_0/W" in r["gradients"]
    # update:param ratios appear from the second full record on
    ratios = storage.update_ratios("layer_0/W", "s1")
    assert ratios and all(r > 0 for r in ratios)
    assert storage.scores("s1") == [rec["score"] for rec in recs]


def test_file_stats_storage_roundtrip(tmp_path):
    path = str(tmp_path / "stats.jsonl")
    storage = FileStatsStorage(path)
    storage.put({"session": "a", "iteration": 0, "score": 1.0})
    storage.put({"session": "b", "iteration": 0, "score": 2.0})
    assert [r["score"] for r in storage.records("a")] == [1.0]
    assert storage.session_ids() == ["a", "b"]
    # appended lines are valid JSONL
    with open(path) as f:
        assert len([json.loads(l) for l in f]) == 2


def test_profiling_listener_chrome_trace(tmp_path):
    path = str(tmp_path / "trace.json")
    model = _model()
    model.add_listeners(ProfilingListener(path))
    x, y = _data()
    model.fit(x, y, epochs=3)
    trace = json.load(open(path))
    events = trace["traceEvents"]
    iters = [e for e in events if e["cat"] == "train"]
    epochs = [e for e in events if e["cat"] == "epoch"]
    assert len(iters) == 3 and len(epochs) == 3
    assert all(e["ph"] == "X" and e["dur"] > 0 for e in events)
    assert iters[0]["args"]["score"] > 0


def test_nan_panic_listener():
    model = _model()
    model.add_listeners(NanPanicListener())
    x, y = _data()
    # poison the params so the first score is NaN
    model.params["layer_0"]["W"] = np.full((4, 8), np.nan, np.float32)
    with pytest.raises(FloatingPointError, match="NaN panic"):
        model.fit(x, y, epochs=1)


def test_evaluative_listener_epoch_end():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator

    model = _model()
    x, y = _data(64)
    it = ListDataSetIterator(DataSet(x, y), 32)
    lst = EvaluativeListener(it, frequency=0, log_fn=lambda *_: None)
    model.add_listeners(lst)
    model.fit(x, y, epochs=3)
    assert len(lst.history) == 3
    assert 0.0 <= lst.history[-1].accuracy() <= 1.0


def test_stats_listener_on_computation_graph():
    """Gradient stats flow on the graph solver too (review regression)."""
    from deeplearning4j_tpu.nn import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.input_type import InputType

    g = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.feed_forward(4)))
    g.add_layer("d", DenseLayer(n_out=8), "in")
    g.add_layer("out", OutputLayer(n_out=2), "d")
    model = ComputationGraph(g.set_outputs("out").build()).init()
    storage = InMemoryStatsStorage()
    model.add_listeners(StatsListener(storage, update_frequency=1))
    x, y = _data()
    model.fit([x], [y], epochs=3)
    full = [r for r in storage.records() if "gradients" in r]
    assert full and "d/W" in full[-1]["gradients"]


def test_ui_server_serves_dashboard_and_stats():
    """UIServer (reference: Vert.x dashboard): attach a storage, GET the
    page and the JSON endpoints over real HTTP."""
    import json
    import urllib.request

    from deeplearning4j_tpu.ui import InMemoryStatsStorage
    from deeplearning4j_tpu.ui.server import UIServer

    storage = InMemoryStatsStorage()
    for i in range(5):
        storage.put({"session": "s1", "iteration": i,
                     "score": 2.0 / (i + 1),
                     "update_ratios": {"layer_0/W": 10.0 ** (-3 + 0.1 * i)}})

    ui = UIServer(port=0).attach(storage).start()
    try:
        base = f"http://127.0.0.1:{ui.port}"
        page = urllib.request.urlopen(base + "/train/overview").read()
        assert b"training UI" in page
        sessions = json.loads(
            urllib.request.urlopen(base + "/train/sessions").read())
        assert sessions == ["s1"]
        stats = json.loads(urllib.request.urlopen(
            base + "/train/stats?sessionId=s1").read())
        assert len(stats["scores"]) == 5
        assert stats["scores"][0] == 2.0
        ratios = stats["update_ratios"]["layer_0/W"]
        assert len(ratios) == 5 and abs(ratios[0] + 3.0) < 1e-6
        assert urllib.request.urlopen(base + "/train/stats").status == 200
    finally:
        ui.stop()
