"""TF GraphDef import golden tests.

Mirrors the reference's TFGraphTestAllSameDiff (SURVEY.md §4): build a TF
graph, freeze it, import to SameDiff, execute both, compare within tolerance.
No network: graphs are built in-process with random weights.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.samediff.tf_import import TFGraphMapper


def freeze(fn, *specs):
    """concrete function -> frozen GraphDef + input/output names."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    cf = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name for t in frozen.outputs]
    return gd, in_names, out_names, frozen


def import_and_compare(fn, feeds_np, rtol=1e-5, atol=1e-6):
    specs = [tf.TensorSpec(v.shape, tf.as_dtype(v.dtype)) for v in feeds_np.values()]
    gd, in_names, out_names, frozen = freeze(fn, *specs)
    tf_out = frozen(**{
        t.name.split(":")[0]: tf.constant(v)
        for t, v in zip(frozen.inputs, feeds_np.values())
    })
    if isinstance(tf_out, (list, tuple)):
        tf_out = tf_out[0]
    sd = TFGraphMapper.import_graph(gd, outputs=out_names)
    sd_feeds = dict(zip(in_names, feeds_np.values()))
    target = out_names[0].split(":")[0]
    ours = np.asarray(sd.output(sd_feeds, [target])[target])
    np.testing.assert_allclose(ours, tf_out.numpy(), rtol=rtol, atol=atol)
    return sd


rng = np.random.default_rng(0)


class TestBasicGraphs:
    def test_mlp(self):
        w1 = tf.constant(rng.normal(size=(8, 16)).astype(np.float32))
        b1 = tf.constant(rng.normal(size=(16,)).astype(np.float32))
        w2 = tf.constant(rng.normal(size=(16, 4)).astype(np.float32))

        def mlp(x):
            h = tf.nn.relu(tf.matmul(x, w1) + b1)
            return tf.nn.softmax(tf.matmul(h, w2))

        import_and_compare(mlp, {"x": rng.normal(size=(5, 8)).astype(np.float32)})

    def test_reductions_and_shapes(self):
        def fn(x):
            y = tf.reshape(x, [2, 3, 4])
            y = tf.transpose(y, [0, 2, 1])
            y = tf.reduce_mean(y, axis=2, keepdims=True)
            return tf.squeeze(y, axis=2)

        import_and_compare(fn, {"x": rng.normal(size=(2, 12)).astype(np.float32)})

    def test_strided_slice_and_concat(self):
        def fn(x):
            a = x[:, 1:3]
            b = x[:, :2]
            return tf.concat([a, b], axis=1)

        import_and_compare(fn, {"x": rng.normal(size=(4, 6)).astype(np.float32)})

    def test_gather_embedding(self):
        table = tf.constant(rng.normal(size=(30, 8)).astype(np.float32))

        def fn(ids):
            return tf.gather(table, ids)

        import_and_compare(fn, {"ids": rng.integers(0, 30, size=(4, 7)).astype(np.int32)})

    def test_layernorm_decomposition(self):
        gamma = tf.constant(rng.normal(size=(16,)).astype(np.float32))
        beta = tf.constant(rng.normal(size=(16,)).astype(np.float32))

        def fn(x):
            mean = tf.reduce_mean(x, axis=-1, keepdims=True)
            var = tf.reduce_mean(tf.math.squared_difference(x, mean), axis=-1, keepdims=True)
            return (x - mean) * tf.math.rsqrt(var + 1e-6) * gamma + beta

        import_and_compare(fn, {"x": rng.normal(size=(3, 16)).astype(np.float32)},
                           rtol=1e-4, atol=1e-5)

    def test_gelu_erf_decomposition(self):
        def fn(x):
            return 0.5 * x * (1.0 + tf.math.erf(x / tf.sqrt(2.0)))

        import_and_compare(fn, {"x": rng.normal(size=(4, 8)).astype(np.float32)})

    def test_conv2d_maxpool(self):
        w = tf.constant(rng.normal(size=(3, 3, 2, 4)).astype(np.float32) * 0.1)

        def fn(x):
            y = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            y = tf.nn.relu(y)
            return tf.nn.max_pool2d(y, 2, 2, padding="VALID")

        import_and_compare(fn, {"x": rng.normal(size=(2, 8, 8, 2)).astype(np.float32)},
                           rtol=1e-4, atol=1e-5)

    def test_onehot_and_cast(self):
        def fn(ids):
            oh = tf.one_hot(ids, depth=5)
            return tf.cast(oh, tf.float32) * 2.0

        import_and_compare(fn, {"ids": rng.integers(0, 5, size=(6,)).astype(np.int32)})

    def test_einsum(self):
        def fn(x):
            w = tf.reshape(tf.range(24, dtype=tf.float32), (4, 6))
            return tf.einsum("bi,ij->bj", x, w)

        import_and_compare(fn, {"x": rng.normal(size=(3, 4)).astype(np.float32)},
                           rtol=1e-4, atol=1e-4)


class TestAttentionGraph:
    def test_mini_self_attention(self):
        """Transformer attention block — the core BERT computation."""
        d, h = 16, 4
        wq = tf.constant(rng.normal(size=(d, d)).astype(np.float32) * 0.1)
        wk = tf.constant(rng.normal(size=(d, d)).astype(np.float32) * 0.1)
        wv = tf.constant(rng.normal(size=(d, d)).astype(np.float32) * 0.1)
        wo = tf.constant(rng.normal(size=(d, d)).astype(np.float32) * 0.1)

        def attn(x):
            b, t = 2, 6
            q = tf.reshape(tf.matmul(tf.reshape(x, [-1, d]), wq), [b, t, h, d // h])
            k = tf.reshape(tf.matmul(tf.reshape(x, [-1, d]), wk), [b, t, h, d // h])
            v = tf.reshape(tf.matmul(tf.reshape(x, [-1, d]), wv), [b, t, h, d // h])
            q = tf.transpose(q, [0, 2, 1, 3])
            k = tf.transpose(k, [0, 2, 1, 3])
            v = tf.transpose(v, [0, 2, 1, 3])
            scores = tf.matmul(q, k, transpose_b=True) / tf.sqrt(tf.cast(d // h, tf.float32))
            w = tf.nn.softmax(scores, axis=-1)
            o = tf.transpose(tf.matmul(w, v), [0, 2, 1, 3])
            o = tf.reshape(o, [b, t, d])
            return tf.matmul(tf.reshape(o, [-1, d]), wo)

        import_and_compare(attn, {"x": rng.normal(size=(2, 6, 16)).astype(np.float32)},
                           rtol=1e-4, atol=1e-5)


class TestTranche3Rules:
    """Golden tests for the tranche-3 rule widening: each new rule family
    executed via TF then via the imported SameDiff graph."""

    def test_special_math_ops(self):
        def f(x):
            return tf.math.lgamma(x) + tf.math.digamma(x) \
                + tf.math.xlogy(x, x + 1.0) + tf.math.atan2(x, x + 2.0)

        import_and_compare(
            f, {"x": (rng.random(size=(3, 4)) + 0.5).astype(np.float32)},
            rtol=1e-4, atol=1e-5)

    def test_depthwise_conv(self):
        w = tf.constant(rng.normal(size=(3, 3, 2, 2)).astype(np.float32) * 0.2)

        def f(x):
            return tf.nn.depthwise_conv2d(x, w, strides=[1, 1, 1, 1],
                                          padding="SAME")

        import_and_compare(
            f, {"x": rng.normal(size=(1, 6, 6, 2)).astype(np.float32)},
            rtol=1e-4, atol=1e-5)

    def test_conv2d_transpose(self):
        w = tf.constant(rng.normal(size=(3, 3, 4, 2)).astype(np.float32) * 0.2)

        def f(x):
            return tf.nn.conv2d_transpose(
                x, w, output_shape=[1, 8, 8, 4], strides=[1, 2, 2, 1],
                padding="SAME")

        import_and_compare(
            f, {"x": rng.normal(size=(1, 4, 4, 2)).astype(np.float32)},
            rtol=1e-4, atol=1e-5)

    def test_resize_and_space_depth(self):
        def f(x):
            y = tf.image.resize(x, [8, 8], method="nearest")
            y = tf.nn.space_to_depth(y, 2)
            return tf.nn.depth_to_space(y, 2)

        import_and_compare(
            f, {"x": rng.normal(size=(1, 4, 4, 3)).astype(np.float32)},
            rtol=1e-5, atol=1e-6)

    def test_segment_ops(self):
        ids = tf.constant(np.asarray([0, 0, 1, 2, 2], np.int32))

        def f(x):
            return tf.math.segment_sum(x, ids)

        import_and_compare(
            f, {"x": rng.normal(size=(5, 3)).astype(np.float32)},
            rtol=1e-5, atol=1e-6)

    def test_unsorted_segment(self):
        ids = tf.constant(np.asarray([2, 0, 1, 0], np.int32))

        def f(x):
            return tf.math.unsorted_segment_sum(x, ids, num_segments=3)

        import_and_compare(
            f, {"x": rng.normal(size=(4, 2)).astype(np.float32)},
            rtol=1e-5, atol=1e-6)

    def test_top_k_values(self):
        def f(x):
            vals, _ = tf.math.top_k(x, k=3)
            return vals

        import_and_compare(
            f, {"x": rng.normal(size=(4, 10)).astype(np.float32)})

    def test_scatter_nd(self):
        idx = tf.constant(np.asarray([[0], [2]], np.int32))

        def f(u):
            return tf.scatter_nd(idx, u, [4, 3])

        import_and_compare(
            f, {"u": rng.normal(size=(2, 3)).astype(np.float32)})

    def test_tensor_scatter_and_band_part(self):
        idx = tf.constant(np.asarray([[0, 0], [1, 2]], np.int32))

        def f(x, u):
            y = tf.tensor_scatter_nd_add(x, idx, u)
            return tf.linalg.band_part(y, 1, 1)

        import_and_compare(
            f, {"x": rng.normal(size=(3, 3)).astype(np.float32),
                "u": rng.normal(size=(2,)).astype(np.float32)})

    def test_linalg_ops(self):
        def f(x):
            s = tf.matmul(x, x, transpose_b=True) + 4.0 * tf.eye(4)
            c = tf.linalg.cholesky(s)
            return tf.linalg.det(s) + tf.reduce_sum(c) \
                + tf.reduce_sum(tf.linalg.inv(s))

        import_and_compare(
            f, {"x": rng.normal(size=(4, 4)).astype(np.float32)},
            rtol=1e-3, atol=1e-3)

    def test_reverse_roll_cumprod(self):
        def f(x):
            y = tf.reverse(x, axis=[1])
            y = tf.roll(y, shift=2, axis=1)
            return tf.math.cumprod(y, axis=1, exclusive=True)

        import_and_compare(
            f, {"x": (rng.random(size=(2, 5)) + 0.5).astype(np.float32)},
            rtol=1e-5, atol=1e-6)

    def test_lrn(self):
        def f(x):
            return tf.nn.local_response_normalization(
                x, depth_radius=2, bias=1.0, alpha=1e-3, beta=0.75)

        import_and_compare(
            f, {"x": rng.normal(size=(1, 4, 4, 8)).astype(np.float32)},
            rtol=1e-4, atol=1e-5)

    def test_fft_real_imag(self):
        def f(x):
            c = tf.signal.fft(tf.complex(x, tf.zeros_like(x)))
            return tf.math.real(c) + tf.math.imag(c)

        import_and_compare(
            f, {"x": rng.normal(size=(2, 8)).astype(np.float32)},
            rtol=1e-3, atol=1e-4)

    def test_clip_and_bitshift(self):
        def f(x):
            return tf.clip_by_value(x, -0.5, 0.5)

        import_and_compare(
            f, {"x": rng.normal(size=(3, 3)).astype(np.float32)})

    def test_qr_svd_eigh_multi_output(self):
        def f(x):
            s_mat = tf.matmul(x, x, transpose_b=True) + 4.0 * tf.eye(4)
            q, r = tf.linalg.qr(x)
            s, u, v = tf.linalg.svd(x)
            w, vec = tf.linalg.eigh(s_mat)
            # combine pieces from every output slot (orders checked via
            # reconstruction, which is basis-invariant)
            recon = tf.matmul(tf.matmul(u, tf.linalg.diag(s)), v,
                              transpose_b=True)
            return tf.reduce_sum(q * 0.0) + tf.reduce_sum(recon) \
                + tf.reduce_sum(w) + tf.reduce_sum(vec * 0.0) \
                + tf.reduce_sum(tf.matmul(q, r))

        import_and_compare(
            f, {"x": rng.normal(size=(4, 4)).astype(np.float32)},
            rtol=1e-3, atol=1e-3)

    def test_conv2d_transpose_odd_size(self):
        # H=W=5 forward with stride 2 SAME -> grads 3x3; the backprop must
        # reconstruct 5, not 6 (the conv_transpose ambiguity).
        w = tf.constant(rng.normal(size=(3, 3, 2, 4)).astype(np.float32) * 0.2)

        def f(g):
            return tf.nn.conv2d_transpose(
                g, tf.transpose(w, [0, 1, 2, 3]) * 1.0,
                output_shape=[1, 5, 5, 2], strides=[1, 2, 2, 1],
                padding="SAME")

        import_and_compare(
            f, {"g": rng.normal(size=(1, 3, 3, 4)).astype(np.float32)},
            rtol=1e-4, atol=1e-5)

    def test_dilated_depthwise_conv(self):
        w = tf.constant(rng.normal(size=(3, 3, 2, 1)).astype(np.float32) * 0.2)

        def f(x):
            return tf.nn.depthwise_conv2d(
                x, w, strides=[1, 1, 1, 1], padding="SAME",
                dilations=[2, 2])

        import_and_compare(
            f, {"x": rng.normal(size=(1, 8, 8, 2)).astype(np.float32)},
            rtol=1e-4, atol=1e-5)

    def test_bincount_weighted(self):
        # raw op with a literal size: tf.math.bincount wraps the size in a
        # Maximum(max(arr)+1, minlength) subgraph, which is dynamic-shape
        # territory the static importer rejects by design.
        arr = tf.constant(np.asarray([0, 1, 1, 3], np.int32))

        def f(w):
            return tf.raw_ops.DenseBincount(
                input=arr, size=tf.constant(5, tf.int32), weights=w,
                binary_output=False)

        import_and_compare(
            f, {"w": rng.normal(size=(4,)).astype(np.float32)})

    def test_batched_matrix_diag_part(self):
        def f(x):
            return tf.linalg.diag_part(x)

        import_and_compare(
            f, {"x": rng.normal(size=(3, 4, 4)).astype(np.float32)})

    def test_resize_bicubic_keys_kernel(self):
        # TF's half-pixel bicubic is Keys a=-0.5 (Catmull-Rom) — exactly
        # jax.image's cubic; a=-0.75 is TF's LEGACY corner-origin kernel,
        # which the importer rejects
        def f(x):
            return tf.image.resize(x, [7, 9], method="bicubic")

        # TF's bicubic kernel is a 1024-entry LUT: ~4e-4 quantization noise
        import_and_compare(
            f, {"x": rng.random(size=(1, 4, 6, 2)).astype(np.float32)},
            rtol=1e-3, atol=1e-3)

    def test_resize_rejects_corner_origin(self):
        def f(x):
            return tf.raw_ops.ResizeBilinear(
                images=x, size=tf.constant([8, 8], tf.int32),
                align_corners=False, half_pixel_centers=False)

        with pytest.raises((NotImplementedError, ValueError)):
            import_and_compare(
                f, {"x": rng.random(size=(1, 4, 4, 1)).astype(np.float32)})
