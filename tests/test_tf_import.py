"""TF GraphDef import golden tests.

Mirrors the reference's TFGraphTestAllSameDiff (SURVEY.md §4): build a TF
graph, freeze it, import to SameDiff, execute both, compare within tolerance.
No network: graphs are built in-process with random weights.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.samediff.tf_import import TFGraphMapper


def freeze(fn, *specs):
    """concrete function -> frozen GraphDef + input/output names."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    cf = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name for t in frozen.outputs]
    return gd, in_names, out_names, frozen


def import_and_compare(fn, feeds_np, rtol=1e-5, atol=1e-6):
    specs = [tf.TensorSpec(v.shape, tf.as_dtype(v.dtype)) for v in feeds_np.values()]
    gd, in_names, out_names, frozen = freeze(fn, *specs)
    tf_out = frozen(**{
        t.name.split(":")[0]: tf.constant(v)
        for t, v in zip(frozen.inputs, feeds_np.values())
    })
    if isinstance(tf_out, (list, tuple)):
        tf_out = tf_out[0]
    sd = TFGraphMapper.import_graph(gd, outputs=out_names)
    sd_feeds = dict(zip(in_names, feeds_np.values()))
    target = out_names[0].split(":")[0]
    ours = np.asarray(sd.output(sd_feeds, [target])[target])
    np.testing.assert_allclose(ours, tf_out.numpy(), rtol=rtol, atol=atol)
    return sd


rng = np.random.default_rng(0)


class TestBasicGraphs:
    def test_mlp(self):
        w1 = tf.constant(rng.normal(size=(8, 16)).astype(np.float32))
        b1 = tf.constant(rng.normal(size=(16,)).astype(np.float32))
        w2 = tf.constant(rng.normal(size=(16, 4)).astype(np.float32))

        def mlp(x):
            h = tf.nn.relu(tf.matmul(x, w1) + b1)
            return tf.nn.softmax(tf.matmul(h, w2))

        import_and_compare(mlp, {"x": rng.normal(size=(5, 8)).astype(np.float32)})

    def test_reductions_and_shapes(self):
        def fn(x):
            y = tf.reshape(x, [2, 3, 4])
            y = tf.transpose(y, [0, 2, 1])
            y = tf.reduce_mean(y, axis=2, keepdims=True)
            return tf.squeeze(y, axis=2)

        import_and_compare(fn, {"x": rng.normal(size=(2, 12)).astype(np.float32)})

    def test_strided_slice_and_concat(self):
        def fn(x):
            a = x[:, 1:3]
            b = x[:, :2]
            return tf.concat([a, b], axis=1)

        import_and_compare(fn, {"x": rng.normal(size=(4, 6)).astype(np.float32)})

    def test_gather_embedding(self):
        table = tf.constant(rng.normal(size=(30, 8)).astype(np.float32))

        def fn(ids):
            return tf.gather(table, ids)

        import_and_compare(fn, {"ids": rng.integers(0, 30, size=(4, 7)).astype(np.int32)})

    def test_layernorm_decomposition(self):
        gamma = tf.constant(rng.normal(size=(16,)).astype(np.float32))
        beta = tf.constant(rng.normal(size=(16,)).astype(np.float32))

        def fn(x):
            mean = tf.reduce_mean(x, axis=-1, keepdims=True)
            var = tf.reduce_mean(tf.math.squared_difference(x, mean), axis=-1, keepdims=True)
            return (x - mean) * tf.math.rsqrt(var + 1e-6) * gamma + beta

        import_and_compare(fn, {"x": rng.normal(size=(3, 16)).astype(np.float32)},
                           rtol=1e-4, atol=1e-5)

    def test_gelu_erf_decomposition(self):
        def fn(x):
            return 0.5 * x * (1.0 + tf.math.erf(x / tf.sqrt(2.0)))

        import_and_compare(fn, {"x": rng.normal(size=(4, 8)).astype(np.float32)})

    def test_conv2d_maxpool(self):
        w = tf.constant(rng.normal(size=(3, 3, 2, 4)).astype(np.float32) * 0.1)

        def fn(x):
            y = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            y = tf.nn.relu(y)
            return tf.nn.max_pool2d(y, 2, 2, padding="VALID")

        import_and_compare(fn, {"x": rng.normal(size=(2, 8, 8, 2)).astype(np.float32)},
                           rtol=1e-4, atol=1e-5)

    def test_onehot_and_cast(self):
        def fn(ids):
            oh = tf.one_hot(ids, depth=5)
            return tf.cast(oh, tf.float32) * 2.0

        import_and_compare(fn, {"ids": rng.integers(0, 5, size=(6,)).astype(np.int32)})

    def test_einsum(self):
        def fn(x):
            w = tf.reshape(tf.range(24, dtype=tf.float32), (4, 6))
            return tf.einsum("bi,ij->bj", x, w)

        import_and_compare(fn, {"x": rng.normal(size=(3, 4)).astype(np.float32)},
                           rtol=1e-4, atol=1e-4)


class TestAttentionGraph:
    def test_mini_self_attention(self):
        """Transformer attention block — the core BERT computation."""
        d, h = 16, 4
        wq = tf.constant(rng.normal(size=(d, d)).astype(np.float32) * 0.1)
        wk = tf.constant(rng.normal(size=(d, d)).astype(np.float32) * 0.1)
        wv = tf.constant(rng.normal(size=(d, d)).astype(np.float32) * 0.1)
        wo = tf.constant(rng.normal(size=(d, d)).astype(np.float32) * 0.1)

        def attn(x):
            b, t = 2, 6
            q = tf.reshape(tf.matmul(tf.reshape(x, [-1, d]), wq), [b, t, h, d // h])
            k = tf.reshape(tf.matmul(tf.reshape(x, [-1, d]), wk), [b, t, h, d // h])
            v = tf.reshape(tf.matmul(tf.reshape(x, [-1, d]), wv), [b, t, h, d // h])
            q = tf.transpose(q, [0, 2, 1, 3])
            k = tf.transpose(k, [0, 2, 1, 3])
            v = tf.transpose(v, [0, 2, 1, 3])
            scores = tf.matmul(q, k, transpose_b=True) / tf.sqrt(tf.cast(d // h, tf.float32))
            w = tf.nn.softmax(scores, axis=-1)
            o = tf.transpose(tf.matmul(w, v), [0, 2, 1, 3])
            o = tf.reshape(o, [b, t, d])
            return tf.matmul(tf.reshape(o, [-1, d]), wo)

        import_and_compare(attn, {"x": rng.normal(size=(2, 6, 16)).astype(np.float32)},
                           rtol=1e-4, atol=1e-5)
