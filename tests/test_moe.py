"""Mixture-of-Experts layer + expert parallelism (SURVEY §2.3 EP row —
absent upstream, implemented TPU-native here via dense one-hot dispatch
and expert-dim sharding)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.nn import (
    Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit,
)
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, MixtureOfExpertsLayer, OutputLayer,
)
from deeplearning4j_tpu.nn.layers.base import LayerContext
from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
from deeplearning4j_tpu.train.solver import Solver
from deeplearning4j_tpu.train.updaters import Sgd


def _layer(e=4, d=8, h=16, o=8, k=1, cap=100.0, mode="sort"):
    lay = MixtureOfExpertsLayer(
        n_in=d, n_out=o, num_experts=e, hidden=h, top_k=k,
        capacity_factor=cap, activation=Activation.RELU,
        dispatch_mode=mode)
    params = lay.init(jax.random.PRNGKey(0), jnp.float32)
    return lay, params


@pytest.mark.parametrize("mode", ["sort", "einsum", "grouped"])
def test_top1_matches_dense_reference(mode):
    """With capacity >= tokens, top-1 MoE output == the argmax expert's MLP
    applied per token (gate weight renormalizes to 1 for k=1)."""
    lay, params = _layer(k=1, mode=mode)
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.rand(12, 8).astype(np.float32))
    y, _ = lay.apply(params, lay.init_state(jnp.float32), x, LayerContext())

    gates = jax.nn.softmax(x @ params["Wg"], axis=-1)
    idx = np.asarray(jnp.argmax(gates, axis=-1))
    ref = np.zeros((12, 8), np.float32)
    for t in range(12):
        e = int(idx[t])
        hdd = np.maximum(
            np.asarray(x[t] @ params["We1"][e] + params["be1"][e]), 0.0)
        ref[t] = np.asarray(hdd @ params["We2"][e] + params["be2"][e])
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_top2_combines_two_experts():
    lay, params = _layer(k=2)
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.rand(6, 8).astype(np.float32))
    y, state = lay.apply(params, lay.init_state(jnp.float32), x,
                         LayerContext())
    assert np.asarray(y).shape == (6, 8)
    assert np.isfinite(np.asarray(y)).all()
    assert float(state["aux_load_balance"]) > 0.0


@pytest.mark.parametrize("mode", ["sort", "einsum", "grouped"])
def test_capacity_drops_overflow_tokens(mode):
    """capacity_factor tiny -> most tokens dropped -> output rows zero."""
    # capacity = ceil(12/4*0.26) = 1
    lay, params = _layer(k=1, cap=0.26, mode=mode)
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.rand(12, 8).astype(np.float32))
    y, _ = lay.apply(params, lay.init_state(jnp.float32), x, LayerContext())
    zero_rows = np.sum(np.all(np.asarray(y) == 0.0, axis=-1))
    assert zero_rows >= 4  # at most one token per expert survives


def test_moe_network_trains():
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.3))
            .weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(MixtureOfExpertsLayer(n_out=16, num_experts=4, hidden=32,
                                         top_k=2))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x = rs.rand(16, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
    s = Solver(net)
    l0 = float(s.fit_batch(x, y)[0])
    l1 = l0
    for _ in range(15):
        l1 = float(s.fit_batch(x, y)[0])
    assert np.isfinite(l1) and l1 < l0


def test_expert_parallel_matches_single_device():
    """EP: expert-dim sharding over the 'model' mesh axis produces the same
    step results as the unsharded run (GSPMD inserts the collectives)."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.trainer import (
        DistributedTrainer, moe_expert_parallel_rules)

    def build():
        conf = (NeuralNetConfiguration.builder().seed(9).updater(Sgd(0.2))
                .weight_init(WeightInit.XAVIER).list()
                .layer(MixtureOfExpertsLayer(n_out=8, num_experts=4,
                                             hidden=16, top_k=2))
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(4)
    x = rs.rand(8, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)]

    ep_rules = moe_expert_parallel_rules("model")
    assert all(P("model") == spec for _, spec in ep_rules)
    t_ep = DistributedTrainer(
        build(), mesh=make_mesh(data=2, model=4),
        param_sharding_rules=ep_rules)
    t_ref = DistributedTrainer(build(), mesh=make_mesh(data=8))

    for _ in range(5):
        s_ep = float(t_ep.fit_batch(x, y))
        s_ref = float(t_ref.fit_batch(x, y))
    np.testing.assert_allclose(s_ep, s_ref, rtol=2e-4)
    for ln in t_ep.params:
        for k in t_ep.params[ln]:
            np.testing.assert_allclose(
                np.asarray(jax.device_get(t_ep.params[ln][k])),
                np.asarray(jax.device_get(t_ref.params[ln][k])),
                rtol=2e-3, atol=2e-5, err_msg=f"{ln}/{k}")


@pytest.mark.parametrize("mode", ["sort", "grouped"])
@pytest.mark.parametrize("zero1", [False, True], ids=["plain", "zero1"])
def test_explicit_expert_parallel_matches_replicated(mode, zero1):
    """Explicit EP (ISSUE 18): expert params sliced over the 'model' axis
    inside the shard_map strategy path — local expert compute + expert-
    axis combine — matches the replicated explicit trainer bit-for-bit
    on scores and params, composed with BucketedAllReduceSync and the
    hand-spelled ZeRO-1 schedule."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.strategies import BucketedAllReduceSync
    from deeplearning4j_tpu.parallel.trainer import (
        DistributedTrainer, moe_expert_parallel_rules)

    def build():
        conf = (NeuralNetConfiguration.builder().seed(9).updater(Sgd(0.2))
                .weight_init(WeightInit.XAVIER).list()
                .layer(MixtureOfExpertsLayer(n_out=8, num_experts=8,
                                             hidden=16, top_k=2,
                                             dispatch_mode=mode))
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(4)
    x = rs.rand(8, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)]

    t_ep = DistributedTrainer(
        build(), mesh=make_mesh(data=2, model=4),
        strategy=BucketedAllReduceSync(), zero1=zero1,
        param_sharding_rules=moe_expert_parallel_rules("model"))
    assert t_ep.ep_shards == 4
    t_ref = DistributedTrainer(
        build(), mesh=make_mesh(data=2, model=4),
        strategy=BucketedAllReduceSync(), zero1=zero1)
    for _ in range(4):
        s_ep = float(t_ep.fit_batch(x, y))
        s_ref = float(t_ref.fit_batch(x, y))
    assert s_ep == s_ref
    for ln in t_ep.params:
        for k in t_ep.params[ln]:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(t_ep.params[ln][k])),
                np.asarray(jax.device_get(t_ref.params[ln][k])),
                err_msg=f"{ln}/{k}")
    # expert slabs really are sliced over the model axis on device
    we1 = t_ep.params[list(t_ep.params)[0]]["We1"]
    shard_shapes = {s.data.shape for s in we1.addressable_shards}
    assert shard_shapes == {(2, 8, 16)}  # 8 experts / 4 shards


def test_explicit_ep_rejects_einsum_mode():
    """dispatch_mode='einsum' has no explicit-EP spelling — fail fast."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.strategies import BucketedAllReduceSync
    from deeplearning4j_tpu.parallel.trainer import (
        DistributedTrainer, moe_expert_parallel_rules)

    conf = (NeuralNetConfiguration.builder().seed(9).updater(Sgd(0.2))
            .weight_init(WeightInit.XAVIER).list()
            .layer(MixtureOfExpertsLayer(n_out=8, num_experts=8, hidden=16,
                                         top_k=2, dispatch_mode="einsum"))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    t = DistributedTrainer(
        net, mesh=make_mesh(data=2, model=4),
        strategy=BucketedAllReduceSync(),
        param_sharding_rules=moe_expert_parallel_rules("model"))
    rs = np.random.RandomState(4)
    x = rs.rand(8, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)]
    with pytest.raises(ValueError, match="einsum"):
        t.fit_batch(x, y)


@pytest.mark.parametrize("mode", ["sort", "einsum", "grouped"])
def test_masked_tokens_claim_no_capacity(mode):
    """Padding tokens (ctx.mask=0) must not consume expert capacity slots
    or influence real-token outputs (recurrent [b, f, t] input path)."""
    lay, params = _layer(k=1, cap=0.5, mode=mode)  # tight capacity
    rs = np.random.RandomState(6)
    b, d, t = 2, 8, 6
    x = np.asarray(rs.rand(b, d, t), np.float32)
    mask = np.ones((b, t), np.float32)
    mask[:, t // 2:] = 0.0  # second half is padding

    # padding CONTENT must be irrelevant: swap it for adversarial values
    # that would (unmasked) win every router argmax and steal all slots
    x2 = x.copy()
    x2[:, :, t // 2:] = 50.0

    y1, state = lay.apply(params, lay.init_state(jnp.float32),
                          jnp.asarray(x), LayerContext(mask=jnp.asarray(mask)))
    y2, _ = lay.apply(params, lay.init_state(jnp.float32),
                      jnp.asarray(x2), LayerContext(mask=jnp.asarray(mask)))
    np.testing.assert_allclose(np.asarray(y1)[:, :, :t // 2],
                               np.asarray(y2)[:, :, :t // 2],
                               rtol=1e-5, atol=1e-6)
    # padding positions get no combine weight -> zero output rows
    np.testing.assert_allclose(np.asarray(y1)[:, :, t // 2:], 0.0, atol=1e-6)
    assert np.isfinite(float(state["aux_load_balance"]))


# ---- round-5 "MoE under load" (VERDICT r4 ask 10) -------------------------


def test_drop_rate_at_realistic_token_counts():
    """4096 tokens, 8 experts, top-2, capacity_factor 1.25: with a skewed
    router some tokens MUST drop; the dispatch tensor's per-token mass
    quantifies the drop rate, which must stay under the worst case implied
    by the capacity bound and hit zero when capacity is generous."""
    e, d, k = 8, 16, 2
    n_tok = 4096
    rs = np.random.RandomState(7)
    # centered features: an all-positive input makes any random router
    # column-mean dominated (one expert wins most tokens by chance)
    x = jnp.asarray(rs.randn(n_tok, d).astype(np.float32))

    def drop_rate(cap, skew):
        lay = MixtureOfExpertsLayer(
            n_in=d, n_out=d, num_experts=e, hidden=32, top_k=k,
            capacity_factor=cap, activation=Activation.RELU)
        params = lay.init(jax.random.PRNGKey(3), jnp.float32)
        # skew the router toward expert 0 so overflow actually occurs
        params["Wg"] = params["Wg"].at[:, 0].add(skew)
        gates = jax.nn.softmax(x @ params["Wg"], axis=-1)
        capacity = int(np.ceil(k * n_tok / e * cap))
        dispatch, combine = lay._route(gates, capacity)
        # per-token assigned slot count, out of k requested
        assigned = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        assert assigned.max() <= k + 1e-6
        dropped = (k - assigned).sum() / (k * n_tok)
        # every surviving combine weight sits in a claimed slot; per-expert
        # fill never exceeds capacity
        assert float(jnp.sum(combine)) <= n_tok + 1e-3
        per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))
        assert per_expert.max() <= capacity + 1e-6
        return float(dropped)

    balanced = drop_rate(1.25, 0.0)
    skewed = drop_rate(1.25, 8.0)
    generous = drop_rate(float(e), 8.0)  # capacity == all tokens
    assert generous == 0.0
    assert skewed > 0.05, "hard-skewed router at cf=1.25 must overflow"
    # a near-uniform random router barely overflows at cf=1.25
    assert balanced < 0.05, balanced
    assert balanced < skewed


def test_balance_loss_weight_improves_balance():
    """With balance_loss_weight > 0 the aux term is part of the training
    score and gradient descent actively flattens expert load; weight 0
    leaves the (deliberately skewed) router skewed."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

    def train(bl_weight, seed=5):
        lb = (NeuralNetConfiguration.builder().seed(seed)
              .updater(Sgd(learning_rate=0.5)).list())
        lb.layer(MixtureOfExpertsLayer(
            n_in=8, n_out=8, num_experts=4, hidden=16, top_k=1,
            capacity_factor=4.0, activation=Activation.RELU,
            balance_loss_weight=bl_weight))
        lb.layer(OutputLayer(n_in=8, n_out=4, activation=Activation.SOFTMAX,
                             loss=LossFunction.MCXENT))
        lb.set_input_type(InputType.feed_forward(8))
        net = MultiLayerNetwork(lb.build()).init()
        # skew the router so imbalance is the starting condition
        # moderate skew: extreme offsets saturate the softmax and kill
        # the aux gradient (gate*(1-gate) -> 0)
        net.params["layer_0"]["Wg"] = \
            net.params["layer_0"]["Wg"] + jnp.asarray(
                np.r_[1.5, np.zeros(3)][None, :], jnp.float32)
        rs = np.random.RandomState(11)
        x = rs.rand(256, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 256)]
        solver = Solver(net)
        for _ in range(80):
            solver.fit_batch(x, y)
        return float(net.state["layer_0"]["aux_load_balance"])

    aux_off = train(0.0)
    aux_on = train(2.0)
    # aux == 1.0 is perfectly balanced (E * sum(frac*mass) with uniform
    # frac=mass=1/E); the trained-with-loss router must be much closer
    assert aux_on < aux_off - 1.0, (aux_on, aux_off)
    assert aux_on < 1.5, aux_on


def test_pre_pr3_state_pytree_migrates_silently():
    """State pytrees from before PR 3 lack the expert_tokens /
    dropped_tokens keys; Solver construction and make_servable must fill
    the defaults via migrate_state (CHANGES.md PR 3 caveat) instead of
    requiring a manual init_state — and existing state values survive."""
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.2))
            .weight_init(WeightInit.XAVIER).list()
            .layer(MixtureOfExpertsLayer(n_out=8, num_experts=2, hidden=16,
                                         top_k=1))
            .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    name = net.conf.layer_name(0)
    marker = jnp.asarray(0.625, net.state[name]["aux_load_balance"].dtype)
    # simulate a restored pre-PR-3 pytree: only aux_load_balance present
    net.state[name] = {"aux_load_balance": marker}
    net._persistent_keys[name] = ("aux_load_balance",)

    rs = np.random.RandomState(3)
    x = rs.rand(8, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 8)]
    # fit() takes the compiled-scan path whose lax.scan carry requires a
    # stable state structure — without migration this raised a carry
    # structure mismatch
    net.fit(x, y, epochs=2)
    st = net.state[name]
    assert set(st) >= {"aux_load_balance", "expert_tokens", "dropped_tokens"}
    assert st["expert_tokens"].shape == (2,)
    out = np.asarray(net.output(x))
    assert np.all(np.isfinite(out))


def test_pre_pr3_state_migrates_in_make_servable():
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    conf = (NeuralNetConfiguration.builder().seed(8).updater(Sgd(0.2))
            .weight_init(WeightInit.XAVIER).list()
            .layer(MixtureOfExpertsLayer(n_out=8, num_experts=2, hidden=16,
                                         top_k=1))
            .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    name = net.conf.layer_name(0)
    net.state[name] = {"aux_load_balance":
                       net.state[name]["aux_load_balance"]}
    net._persistent_keys[name] = ("aux_load_balance",)
    pi = ParallelInference(net, workers=1, batch_limit=4)
    try:
        x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        out = pi.output_async(x).result(timeout=30)
        assert np.all(np.isfinite(np.asarray(out)))
        assert "expert_tokens" in net.state[name]
    finally:
        pi.shutdown(drain=False)
