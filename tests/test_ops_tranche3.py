"""Tranche-3 SameDiff ops vs independent references (numpy/torch/manual
math) — one representative per family plus the tricky-semantics ops
(dilation2d, im2col/col2im adjointness, dynamic_stitch, updaters, SSIM,
CTC greedy decode, cyclic bit shifts)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.samediff.ops import SD_OPS, get_sd_op


def op(name, *args, **kw):
    out = get_sd_op(name)(*[jnp.asarray(a) if isinstance(a, np.ndarray) else a
                            for a in args], **kw)
    return np.asarray(out)


def test_registry_breadth_tranche3():
    assert len(SD_OPS) >= 490, f"op registry at {len(SD_OPS)}"


def test_pairwise_long_tail():
    a = np.asarray([3.0, -7.5, 2.0])
    b = np.asarray([2.0, 2.0, -4.0])
    np.testing.assert_allclose(op("rsub", a, b), b - a)
    np.testing.assert_allclose(op("rdiv", a, b), b / a)
    np.testing.assert_allclose(op("truncatediv", a, b), np.trunc(a / b))
    np.testing.assert_allclose(op("truncatemod", a, b), np.fmod(a, b))
    np.testing.assert_allclose(op("floormod", a, b), np.mod(a, b))
    np.testing.assert_allclose(
        op("div_no_nan", a, np.asarray([2.0, 0.0, 1.0])), [1.5, 0.0, 2.0])
    np.testing.assert_allclose(op("axpy", a, b, alpha=2.0), 2 * a + b)
    np.testing.assert_allclose(
        op("relative_error", np.asarray([0.0, 1.0]), np.asarray([0.0, 3.0])),
        [0.0, 0.5])


def test_reduce3_distances():
    x = np.random.RandomState(0).rand(4, 8)
    y = np.random.RandomState(1).rand(4, 8)
    np.testing.assert_allclose(
        op("euclidean_distance", x, y, axis=1),
        np.linalg.norm(x - y, axis=1), rtol=1e-6)
    np.testing.assert_allclose(
        op("manhattan_distance", x, y, axis=1),
        np.abs(x - y).sum(axis=1), rtol=1e-6)
    cs = (x * y).sum(1) / (np.linalg.norm(x, axis=1) * np.linalg.norm(y, axis=1))
    np.testing.assert_allclose(op("cosine_similarity", x, y, axis=1), cs,
                               rtol=1e-5)
    np.testing.assert_allclose(
        op("hamming_distance", np.asarray([1, 2, 3]), np.asarray([1, 9, 3])),
        1.0)
    jd = 1 - np.minimum(x, y).sum(1) / np.maximum(x, y).sum(1)
    np.testing.assert_allclose(op("jaccard_distance", x, y, axis=1), jd,
                               rtol=1e-5)


def test_dot_product_attention_vs_manual():
    rs = np.random.RandomState(2)
    q, k, v = (rs.rand(2, 5, 4).astype(np.float32) for _ in range(3))
    got = op("dot_product_attention", q, k, v)
    logits = q @ k.transpose(0, 2, 1) / np.sqrt(4)
    w = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, w @ v, rtol=1e-5, atol=1e-6)


def test_merge_and_stitch():
    xs = [np.asarray([1.0, 5.0]), np.asarray([4.0, 2.0]),
          np.asarray([3.0, 3.0])]
    np.testing.assert_allclose(op("mergeadd", *xs), [8.0, 10.0])
    np.testing.assert_allclose(op("mergemax", *xs), [4.0, 5.0])
    np.testing.assert_allclose(op("mergeavg", *xs), [8 / 3, 10 / 3])
    np.testing.assert_allclose(op("mergemaxindex", *xs), [1, 0])
    got = get_sd_op("dynamic_stitch")(
        [jnp.asarray([0, 2]), jnp.asarray([1, 3])],
        jnp.asarray([[10.0], [30.0]]), jnp.asarray([[20.0], [40.0]]))
    np.testing.assert_allclose(np.asarray(got),
                               [[10.0], [20.0], [30.0], [40.0]])


def test_depthwise_and_separable_conv_vs_torch():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(3)
    x = rs.rand(2, 8, 8, 3).astype(np.float32)
    wd = rs.rand(3, 3, 3, 2).astype(np.float32)  # kH kW C mult
    got = op("depthwise_conv2d", x, wd, strides=(1, 1), padding="SAME")
    tx = torch.tensor(x.transpose(0, 3, 1, 2))
    # torch depthwise: weight [C*mult, 1, kH, kW], groups=C
    tw = torch.tensor(wd.transpose(2, 3, 0, 1).reshape(6, 1, 3, 3))
    ref = torch.nn.functional.conv2d(tx, tw, padding=1, groups=3)
    np.testing.assert_allclose(got, ref.numpy().transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-5)

    wp = rs.rand(1, 1, 6, 4).astype(np.float32)
    got_sep = op("separable_conv2d", x, wd, wp, padding="SAME")
    ref_sep = torch.nn.functional.conv2d(
        ref, torch.tensor(wp[0, 0].T[:, :, None, None]))
    np.testing.assert_allclose(got_sep, ref_sep.numpy().transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-4)


def test_dilation2d_vs_manual():
    rs = np.random.RandomState(4)
    x = rs.rand(1, 5, 5, 1).astype(np.float32)
    w = rs.rand(3, 3, 1).astype(np.float32)
    got = op("dilation2d", x, w, strides=(1, 1), rates=(1, 1),
             padding="VALID")
    ref = np.zeros((1, 3, 3, 1), np.float32)
    for i in range(3):
        for j in range(3):
            ref[0, i, j, 0] = np.max(x[0, i:i + 3, j:j + 3, 0] + w[:, :, 0])
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_im2col_col2im_adjoint():
    """col2im is the adjoint of im2col: <im2col(x), c> == <x, col2im(c)>."""
    rs = np.random.RandomState(5)
    x = rs.rand(1, 2, 6, 6).astype(np.float32)
    cols_shape = op("im2col", x, kernel=(3, 3), strides=(2, 2),
                    padding="VALID").shape
    c = rs.rand(*cols_shape).astype(np.float32)
    lhs = float((op("im2col", x, kernel=(3, 3), strides=(2, 2),
                    padding="VALID") * c).sum())
    back = op("col2im", c, output_size=(6, 6), kernel=(3, 3), strides=(2, 2),
              padding="VALID")
    rhs = float((x * back).sum())
    assert abs(lhs - rhs) < 1e-2 * max(abs(lhs), 1.0)


def test_max_pool_with_argmax_and_unpool():
    x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
    pooled, arg = get_sd_op("max_pool_with_argmax")(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(pooled),
                               [[[[5.0], [7.0]], [[13.0], [15.0]]]])
    restored = op("max_unpooling2d", np.asarray(pooled), np.asarray(arg),
                  input_shape=(1, 4, 4, 1))
    assert restored[0, 1, 1, 0] == 5.0 and restored[0, 3, 3, 0] == 15.0
    assert restored.sum() == 5.0 + 7.0 + 13.0 + 15.0


def test_lstm_layer_matches_cell_loop():
    rs = np.random.RandomState(6)
    T, B, I, U = 5, 2, 3, 4
    x = rs.rand(T, B, I).astype(np.float32)
    W = rs.rand(I, 4 * U).astype(np.float32) * 0.3
    R = rs.rand(U, 4 * U).astype(np.float32) * 0.3
    h = np.zeros((B, U), np.float32)
    c = np.zeros((B, U), np.float32)
    cell = get_sd_op("lstm_cell")
    hs_ref = []
    hj, cj = jnp.asarray(h), jnp.asarray(c)
    for t in range(T):
        hj, cj = cell(jnp.asarray(x[t]), hj, cj, jnp.asarray(W),
                      jnp.asarray(R))
        hs_ref.append(np.asarray(hj))
    hs, hT, cT = get_sd_op("lstm_layer")(
        jnp.asarray(x), jnp.asarray(h), jnp.asarray(c), jnp.asarray(W),
        jnp.asarray(R))
    np.testing.assert_allclose(np.asarray(hs), np.stack(hs_ref), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(hT), hs_ref[-1], rtol=1e-5,
                               atol=1e-6)


def test_sru_and_gru_and_bidirectional_shapes():
    rs = np.random.RandomState(7)
    T, B, D = 6, 2, 4
    x = rs.rand(T, B, D).astype(np.float32)
    hs, cT = get_sd_op("sru")(
        jnp.asarray(x), jnp.zeros((B, D)), jnp.asarray(rs.rand(D, 3 * D),),
        jnp.asarray(rs.rand(2 * D)))
    assert np.asarray(hs).shape == (T, B, D)
    assert np.all(np.isfinite(np.asarray(hs)))
    W = rs.rand(D, 3 * D).astype(np.float32)
    R = rs.rand(D, 3 * D).astype(np.float32)
    hs2, hT2 = get_sd_op("gru")(jnp.asarray(x), jnp.zeros((B, D)),
                                jnp.asarray(W), jnp.asarray(R))
    np.testing.assert_allclose(
        np.asarray(hT2),
        np.asarray(get_sd_op("gru_cell")(
            jnp.asarray(x[-1]), jnp.asarray(np.asarray(hs2)[-2]),
            jnp.asarray(W), jnp.asarray(R))), rtol=1e-5, atol=1e-6)
    Wl = rs.rand(D, 4 * D).astype(np.float32)
    Rl = rs.rand(D, 4 * D).astype(np.float32)
    bi = get_sd_op("bidirectional_lstm")(
        jnp.asarray(x), jnp.zeros((B, D)), jnp.zeros((B, D)),
        jnp.zeros((B, D)), jnp.zeros((B, D)), jnp.asarray(Wl),
        jnp.asarray(Rl), jnp.asarray(Wl), jnp.asarray(Rl))
    assert np.asarray(bi).shape == (T, B, 2 * D)


def test_fft_family():
    rs = np.random.RandomState(8)
    x = rs.rand(8).astype(np.float32)
    np.testing.assert_allclose(op("fft", x), np.fft.fft(x), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.real(op("ifft", op("fft", x))), x,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(op("rfft", x), np.fft.rfft(x), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(op("irfft", np.fft.rfft(x)), x, rtol=1e-4,
                               atol=1e-5)
    c = np.fft.fft(x)
    np.testing.assert_allclose(op("real", c), c.real, rtol=1e-6)
    np.testing.assert_allclose(op("imag", c), c.imag, rtol=1e-6)
    np.testing.assert_allclose(op("angle", c), np.angle(c), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(op("fftshift", x), np.fft.fftshift(x))


def test_windows_and_stft():
    for name, ref in [("hann_window", np.hanning),
                      ("hamming_window", np.hamming),
                      ("blackman_window", np.blackman),
                      ("bartlett_window", np.bartlett)]:
        # symmetric form == the numpy windows
        np.testing.assert_allclose(op(name, 16, periodic=False), ref(16),
                                   atol=1e-5, err_msg=name)
    # periodic (TF-signal default) == symmetric window of N+1, truncated
    np.testing.assert_allclose(op("hann_window", 16),
                               np.hanning(17)[:16], atol=1e-5)
    rs = np.random.RandomState(9)
    sig = rs.rand(512).astype(np.float32)
    s = op("stft", sig, frame_length=64, frame_step=32)
    assert s.shape == (15, 33)
    manual = np.fft.rfft(sig[:64] * np.hanning(65)[:64])
    np.testing.assert_allclose(s[0], manual, rtol=1e-3, atol=1e-3)


def test_bessel_and_special():
    x = np.asarray([0.0, 0.5, 1.0, 2.0])
    np.testing.assert_allclose(op("bessel_i0", x), np.i0(x), rtol=1e-5)
    assert abs(op("bessel_i1", np.asarray([0.0]))[()]) < 1e-7
    np.testing.assert_allclose(op("sinc", x), np.sinc(x), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(op("ndtr", np.asarray([0.0])), [0.5])
    np.testing.assert_allclose(
        op("ndtri", op("ndtr", np.asarray([0.7]))), [0.7], rtol=1e-4)


def test_image_geometry():
    rs = np.random.RandomState(10)
    img = rs.rand(1, 6, 8, 3).astype(np.float32)
    np.testing.assert_allclose(op("flip_left_right", img), img[:, :, ::-1])
    np.testing.assert_allclose(op("flip_up_down", img), img[:, ::-1])
    np.testing.assert_allclose(op("rot90", img, k=1),
                               np.rot90(img, 1, axes=(1, 2)))
    cc = op("central_crop", img, fraction=0.5)
    assert cc.shape == (1, 3, 4, 3)
    crop = op("crop_to_bounding_box", img, 1, 2, 4, 5)
    np.testing.assert_allclose(crop, img[:, 1:5, 2:7])
    padded = op("pad_to_bounding_box", img, 1, 1, 8, 10)
    assert padded.shape == (1, 8, 10, 3)
    np.testing.assert_allclose(padded[:, 1:7, 1:9], img)
    mp = op("mirror_pad", img[0, :, :, 0], paddings=[[1, 1], [2, 2]],
            mode="REFLECT")
    np.testing.assert_allclose(mp, np.pad(img[0, :, :, 0], ((1, 1), (2, 2)),
                                          mode="reflect"))


def test_image_photometric_and_quality():
    rs = np.random.RandomState(11)
    a = rs.rand(1, 16, 16, 1).astype(np.float32)
    np.testing.assert_allclose(op("adjust_gamma", a, gamma=2.0, gain=3.0),
                               3.0 * a ** 2, rtol=1e-5)
    # psnr of identical images is inf; of a known offset it's closed-form
    b = np.clip(a + 0.1, 0, 2)
    mse = np.mean((a - b) ** 2)
    np.testing.assert_allclose(op("psnr", a, b), 10 * np.log10(1 / mse),
                               rtol=1e-4)
    s = op("ssim", a, a)
    np.testing.assert_allclose(s, [1.0], atol=1e-5)
    assert float(op("ssim", a, b)[0]) < 1.0
    dy, dx = get_sd_op("image_gradients")(jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(dy)[0, :-1, :, 0],
                               a[0, 1:, :, 0] - a[0, :-1, :, 0], atol=1e-6)
    tv = op("total_variation", a)
    assert tv.shape == (1,) and tv[0] > 0
    # yiq/yuv round-trips
    rgb = rs.rand(4, 3).astype(np.float32)
    np.testing.assert_allclose(op("yiq_to_rgb", op("rgb_to_yiq", rgb)), rgb,
                               atol=1e-4)
    np.testing.assert_allclose(
        op("yuv_to_rgb", get_sd_op("rgb_to_yuv")(jnp.asarray(rgb))), rgb,
        atol=1e-4)


def test_sobel_on_gradient_image():
    img = np.tile(np.arange(8, dtype=np.float32)[None, None, :, None],
                  (1, 8, 1, 1))  # horizontal ramp
    edges = op("sobel_edges", img)
    assert edges.shape == (1, 8, 8, 1, 2)
    interior = edges[0, 2:-2, 2:-2, 0]
    np.testing.assert_allclose(interior[..., 0], 0.0, atol=1e-5)  # dy
    np.testing.assert_allclose(interior[..., 1], 8.0, atol=1e-4)  # dx (4*dx2)


def test_scatter_nd_family():
    idx = np.asarray([[0], [2]])
    upd = np.asarray([[1.0, 2.0], [3.0, 4.0]])
    got = op("scatter_nd", idx, upd, shape=(4, 2))
    np.testing.assert_allclose(got, [[1, 2], [0, 0], [3, 4], [0, 0]])
    ref = np.ones((4, 2), np.float32)
    np.testing.assert_allclose(op("scatter_nd_add", ref, idx, upd),
                               [[2, 3], [1, 1], [4, 5], [1, 1]])
    np.testing.assert_allclose(op("scatter_nd_update", ref, idx, upd),
                               [[1, 2], [1, 1], [3, 4], [1, 1]])


def test_updater_ops_vs_manual():
    g = np.asarray([0.5, -1.0], np.float32)
    np.testing.assert_allclose(op("sgd_updater", g, lr=0.1), 0.1 * g)
    upd, v = get_sd_op("momentum_updater")(jnp.asarray(g),
                                           jnp.zeros(2), lr=0.1, momentum=0.9)
    np.testing.assert_allclose(np.asarray(upd), 0.1 * g)
    # adam step 0 reduces to lr * sign-ish formula
    upd, m2, v2 = get_sd_op("adam_updater")(
        jnp.asarray(g), jnp.zeros(2), jnp.zeros(2), 0, lr=1e-3)
    mhat = (0.1 * g) / (1 - 0.9)
    vhat = (0.001 * g ** 2) / (1 - 0.999)
    np.testing.assert_allclose(np.asarray(upd),
                               1e-3 * mhat / (np.sqrt(vhat) + 1e-8),
                               rtol=1e-5)
    # adagrad accumulates squared grads
    upd, s = get_sd_op("adagrad_updater")(jnp.asarray(g), jnp.ones(2),
                                          lr=0.1)
    np.testing.assert_allclose(np.asarray(s), 1 + g ** 2, rtol=1e-6)
    # rmsprop / adadelta / adamax / amsgrad / nadam: finite + state shapes
    for name, extra in [("rmsprop_updater", (jnp.zeros(2),)),
                        ("adadelta_updater", (jnp.zeros(2), jnp.zeros(2))),
                        ("adamax_updater", (jnp.zeros(2), jnp.zeros(2), 0)),
                        ("amsgrad_updater",
                         (jnp.zeros(2), jnp.zeros(2), jnp.zeros(2), 0)),
                        ("nadam_updater", (jnp.zeros(2), jnp.zeros(2), 0))]:
        outs = get_sd_op(name)(jnp.asarray(g), *extra)
        assert np.all(np.isfinite(np.asarray(outs[0]))), name


def test_nan_reductions():
    x = np.asarray([[1.0, np.nan, 3.0], [np.nan, 5.0, 6.0]])
    np.testing.assert_allclose(op("nansum", x, axis=1), [4.0, 11.0])
    np.testing.assert_allclose(op("nanmean", x, axis=1), [2.0, 5.5])
    np.testing.assert_allclose(op("nanmax", x), 6.0)
    np.testing.assert_allclose(op("nanmin", x, axis=0), [1.0, 5.0, 3.0])


def test_statistics():
    rs = np.random.RandomState(12)
    x = rs.rand(3, 50)
    np.testing.assert_allclose(op("cov", x), np.cov(x), rtol=1e-5)
    np.testing.assert_allclose(op("corrcoef", x), np.corrcoef(x), rtol=1e-5)
    np.testing.assert_allclose(op("quantile", x[0], 0.25),
                               np.quantile(x[0], 0.25), rtol=1e-5)
    np.testing.assert_allclose(op("ptp", x[0]), np.ptp(x[0]), rtol=1e-6)
    np.testing.assert_allclose(op("diff", x[0]), np.diff(x[0]), rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(op("trapz", x[0]), np.trapezoid(x[0]),
                               rtol=1e-5)
    assert bool(op("allclose", x, x.copy()))
    np.testing.assert_allclose(
        op("zero_fraction", np.asarray([0.0, 1.0, 0.0, 2.0])), 0.5)
    m, v = get_sd_op("weighted_moments")(
        jnp.asarray(x[0]), jnp.ones_like(jnp.asarray(x[0])), axis=0)
    np.testing.assert_allclose(np.asarray(m), x[0].mean(), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(v), x[0].var(), rtol=1e-4)


def test_indexing_family():
    x = np.asarray([[3.0, 7.0, 7.0, 1.0]])
    assert op("first_index", x, 7.0).tolist() == [1]
    assert op("last_index", x, 7.0).tolist() == [2]
    assert op("first_index", x, 99.0).tolist() == [-1]
    np.testing.assert_allclose(op("ismax", x, axis=1), [[0, 1, 1, 0]])
    assert float(op("nth_element", x[0], 1)) == 3.0
    assert float(op("nth_element", x[0], 0, reverse=True)) == 7.0
    vals, n = get_sd_op("choose")(jnp.asarray(x[0]), condition="gt",
                                  value=2.0)
    assert int(n) == 3 and sorted(np.asarray(vals)[:3].tolist()) == [3, 7, 7]
    diff, n2 = get_sd_op("setdiff1d_padded")(
        jnp.asarray([1, 2, 3, 4]), jnp.asarray([2, 4]))
    assert int(n2) == 2 and np.asarray(diff)[:2].tolist() == [1, 3]
    p = np.asarray([2, 0, 1])
    np.testing.assert_allclose(op("invert_permutation", p), [1, 2, 0])
    np.testing.assert_allclose(
        op("take_along_axis", x, np.asarray([[3, 0]]), axis=1), [[1.0, 3.0]])


def test_bitwise_extras():
    x = np.asarray([0b1011], np.int32)
    np.testing.assert_array_equal(op("toggle_bits", x), ~x)
    got = op("cyclic_shift_bits", np.asarray([1], np.int32), 33)
    np.testing.assert_array_equal(got, [2])  # 33 % 32 == 1
    got = op("cyclic_rshift_bits", np.asarray([1], np.int32), 1)
    np.testing.assert_array_equal(
        got, np.asarray([np.uint32(1 << 31)]).astype(np.int32))
    assert int(op("bits_hamming_distance", np.asarray([0b1010], np.int32),
                  np.asarray([0b0110], np.int32))) == 2


def test_loss_extras():
    lab = np.asarray([[1.0, 0.0], [0.0, 1.0]])
    pred = np.asarray([[0.8, 0.1], [0.2, 0.7]])
    np.testing.assert_allclose(op("absolute_difference_loss", lab, pred),
                               np.abs(pred - lab).mean(), rtol=1e-6)
    np.testing.assert_allclose(op("l2_loss", pred),
                               0.5 * (pred ** 2).sum(), rtol=1e-6)
    lp = op("log_poisson_loss", np.asarray([2.0]), np.asarray([0.5]))
    np.testing.assert_allclose(lp, np.exp(0.5) - 2 * 0.5, rtol=1e-5)
    x = np.asarray([[1.0, 2.0]])
    w = np.asarray([[0.5], [0.25]])
    b = np.asarray([1.0])
    np.testing.assert_allclose(op("xw_plus_b", x, w, b), [[2.0]])
    np.testing.assert_allclose(op("relu_layer", x, -w, b), [[0.0]])


def test_activation_long_tail_vs_torch():
    torch = pytest.importorskip("torch")
    x = np.linspace(-3, 3, 13).astype(np.float32)
    tx = torch.tensor(x)
    f = torch.nn.functional
    for name, ref in [("celu", f.celu), ("hard_swish", f.hardswish),
                      ("hardshrink", f.hardshrink),
                      ("softshrink", f.softshrink),
                      ("tanhshrink", f.tanhshrink)]:
        np.testing.assert_allclose(op(name, x), ref(tx).numpy(), atol=1e-5,
                                   err_msg=name)
    np.testing.assert_allclose(op("glu", x[:12]),
                               f.glu(tx[:12]).numpy(), atol=1e-5)
    np.testing.assert_allclose(op("crelu", x).reshape(-1),
                               np.concatenate([np.maximum(x, 0),
                                               np.maximum(-x, 0)]), atol=1e-6)
    np.testing.assert_allclose(op("gelu_precise", x),
                               f.gelu(tx).numpy(), atol=1e-5)


def test_quantization():
    x = np.asarray([-10.0, -1.0, 0.0, 0.5, 10.0], np.float32)
    fq = op("fake_quant_with_min_max_args", x, min=-1.0, max=1.0)
    # TF nudges min/max so zero is exactly representable; the clamped range
    # may exceed [min, max] by up to one quantization step (2/255 here).
    step = 2.0 / 255.0
    assert fq.min() >= -1.0 - step and fq.max() <= 1.0 + step
    assert float(fq[2]) == 0.0  # zero exactly representable after nudging
    # quantize/dequantize round-trip within one step
    q = op("quantize", np.asarray([0.2, 0.7]), scale=0.1)
    dq = op("dequantize", q, scale=0.1)
    np.testing.assert_allclose(dq, [0.2, 0.7], atol=0.05)


def test_linalg_extras():
    rs = np.random.RandomState(13)
    a = rs.rand(4, 4)
    s = a @ a.T + 4 * np.eye(4)
    w, v = get_sd_op("self_adjoint_eig")(jnp.asarray(s))
    np.testing.assert_allclose(np.asarray(v) @ np.diag(np.asarray(w))
                               @ np.asarray(v).T, s, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(op("eigvalsh", s), np.linalg.eigvalsh(s),
                               rtol=1e-5)
    np.testing.assert_allclose(op("matrix_power", a, 3),
                               np.linalg.matrix_power(a, 3), rtol=1e-4)
    chol = np.linalg.cholesky(s)
    rhs = rs.rand(4, 2)
    np.testing.assert_allclose(op("cholesky_solve", chol, rhs),
                               np.linalg.solve(s, rhs), rtol=1e-4, atol=1e-5)
    b = rs.rand(4, 3)
    np.testing.assert_allclose(
        op("mmul_transpose", a, b, transpose_a=True), a.T @ b, rtol=1e-5)
    np.testing.assert_allclose(
        op("tensormmul", a, b, axes_a=[1], axes_b=[0]), a @ b, rtol=1e-5)
    np.testing.assert_allclose(op("tri", 3, k=0), np.tri(3))


def test_creation_and_random_extras():
    assert op("zeros", shape=(2, 3)).shape == (2, 3)
    assert op("ones", shape=(2,)).tolist() == [1.0, 1.0]
    np.testing.assert_allclose(op("logspace", 0.0, 2.0, num=3),
                               [1.0, 10.0, 100.0], rtol=1e-5)
    np.testing.assert_allclose(op("geomspace", 1.0, 8.0, num=4),
                               [1, 2, 4, 8], rtol=1e-5)
    rng = jax.random.PRNGKey(0)
    bern = get_sd_op("random_binomial")(shape=(2000,), n=10, p=0.3, rng=rng)
    assert abs(float(jnp.mean(bern)) - 3.0) < 0.2
    logits = jnp.log(jnp.asarray([[0.05, 0.9, 0.05]] * 4))
    samp = get_sd_op("random_multinomial")(logits, num_samples=50, rng=rng)
    assert np.asarray(samp).shape == (4, 50)
    assert (np.asarray(samp) == 1).mean() > 0.6


def test_ctc_greedy_decoder():
    # logits for sequence [blank, a, a, blank, b] -> decode [a, b]
    C = 3  # 0=blank
    seq = [0, 1, 1, 0, 2]
    logits = np.full((1, 5, C), -5.0, np.float32)
    for t, s in enumerate(seq):
        logits[0, t, s] = 5.0
    dec, lens = get_sd_op("ctc_greedy_decoder")(jnp.asarray(logits))
    assert int(lens[0]) == 2
    assert np.asarray(dec)[0, :2].tolist() == [1, 2]


def test_cumulative_extras():
    x = np.asarray([3.0, 1.0, 4.0, 1.0, 5.0])
    np.testing.assert_allclose(op("cummax", x), np.maximum.accumulate(x))
    np.testing.assert_allclose(op("cummin", x), np.minimum.accumulate(x))
    np.testing.assert_allclose(
        op("cumlogsumexp", x),
        np.log(np.cumsum(np.exp(x))), rtol=1e-5)


def test_fused_batch_norm():
    rs = np.random.RandomState(14)
    x = rs.rand(2, 4, 4, 3).astype(np.float32)
    y, m, v = get_sd_op("fused_batch_norm")(
        jnp.asarray(x), jnp.ones(3), jnp.zeros(3), epsilon=1e-5)
    np.testing.assert_allclose(np.asarray(m), x.mean(axis=(0, 1, 2)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(y).mean(axis=(0, 1, 2)),
                               np.zeros(3), atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(axis=(0, 1, 2)),
                               np.ones(3), atol=1e-3)


def test_bincount_per_row_and_binary():
    x = np.asarray([[0, 1, 1], [2, 2, 2]], np.int32)
    got = op("bincount", x, minlength=4)
    np.testing.assert_allclose(got, [[1, 2, 0, 0], [0, 0, 3, 0]])
    got_bin = op("bincount", x, minlength=4, binary_output=True)
    np.testing.assert_allclose(got_bin, [[1, 1, 0, 0], [0, 0, 1, 0]])
    w = np.asarray([[0.5, 1.0, 2.0], [1.0, 1.0, 1.0]], np.float32)
    got_w = op("bincount_weighted", x, w, minlength=4)
    np.testing.assert_allclose(got_w, [[0.5, 3.0, 0, 0], [0, 0, 3.0, 0]])


def test_sufficient_statistics_default_axis():
    x = np.asarray([[1.0, 2.0], [3.0, 4.0]])
    cnt, s, ss, _ = get_sd_op("sufficient_statistics")(jnp.asarray(x))
    assert float(cnt) == 4.0 and float(s) == 10.0 and float(ss) == 30.0
    m, v = get_sd_op("weighted_moments")(jnp.asarray(x),
                                         jnp.ones_like(jnp.asarray(x)))
    np.testing.assert_allclose(float(m), 2.5)
    np.testing.assert_allclose(float(v), 1.25)


def test_div_no_nan_gradient_safe():
    g = jax.grad(lambda a, b: jnp.sum(get_sd_op("div_no_nan")(a, b)),
                 argnums=(0, 1))(jnp.asarray([1.0, 2.0]),
                                 jnp.asarray([0.0, 4.0]))
    assert np.all(np.isfinite(np.asarray(g[0])))
    assert np.all(np.isfinite(np.asarray(g[1])))
    np.testing.assert_allclose(np.asarray(g[0]), [0.0, 0.25])


def test_cyclic_shift_signed_int8():
    got = op("cyclic_shift_bits", np.asarray([-127], np.int8), 1)  # 0x81
    np.testing.assert_array_equal(got, [3])
    got = op("cyclic_rshift_bits", np.asarray([1], np.int8), 1)
    np.testing.assert_array_equal(got, [np.int8(-128)])  # 0x80


def test_dynamic_stitch_last_wins():
    got = get_sd_op("dynamic_stitch")(
        [jnp.asarray([0, 1]), jnp.asarray([0])],
        jnp.asarray([[1.0], [2.0]]), jnp.asarray([[9.0]]), size=2)
    np.testing.assert_allclose(np.asarray(got), [[9.0], [2.0]])


def test_dynamic_stitch_concrete_gaps_and_duplicates_no_size():
    """TF semantics without size=: n = max(indices)+1, gaps stay zero,
    duplicates keep last-wins (ADVICE round-5 item 1 — TF-imported graphs
    legally use gaps/duplicates and the importer cannot pass size=)."""
    op = get_sd_op("dynamic_stitch")
    # gap: index 1 never written -> zero row, length = max+1 = 4
    got = op([jnp.asarray([0, 2]), jnp.asarray([3])],
             jnp.asarray([[1.0], [3.0]]), jnp.asarray([[7.0]]))
    np.testing.assert_allclose(np.asarray(got), [[1.0], [0.0], [3.0], [7.0]])
    # duplicate across lists: later list wins
    got = op([jnp.asarray([0, 1]), jnp.asarray([0])],
             jnp.asarray([[1.0], [2.0]]), jnp.asarray([[9.0]]))
    np.testing.assert_allclose(np.asarray(got), [[9.0], [2.0]])


def test_dynamic_stitch_traced_indices_require_size():
    op = get_sd_op("dynamic_stitch")

    def stitched(idx):
        return op([idx], jnp.asarray([[1.0], [2.0]]))

    with pytest.raises(ValueError, match="traced indices"):
        jax.jit(stitched)(jnp.asarray([0, 1]))
    # with size= the traced form works
    out = jax.jit(lambda idx: op([idx], jnp.asarray([[1.0], [2.0]]),
                                 size=2))(jnp.asarray([1, 0]))
    np.testing.assert_allclose(np.asarray(out), [[2.0], [1.0]])


def test_fake_quant_vars_jittable():
    f = jax.jit(lambda x, lo, hi:
                get_sd_op("fake_quant_with_min_max_vars")(x, lo, hi))
    out = f(jnp.asarray([0.3, 2.0]), jnp.asarray(-1.0), jnp.asarray(1.0))
    assert np.all(np.isfinite(np.asarray(out)))
