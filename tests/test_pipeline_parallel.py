"""Pipeline parallelism (SURVEY §2.3 PP row — absent upstream): the GPipe
microbatch schedule over a 'pipe' mesh axis must match folding the stages
sequentially, in both the forward values and the gradients. The tick
schedules (gpipe fill–drain and interleaved 1F1B) are additionally checked
against their analytic bubble bound (S-1)/(M+S-1) and the 1F1B O(S)
resident-activation guarantee."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import (
    SCHEDULES,
    build_pipeline_schedule,
    dense_block_stage,
    pipeline_apply,
    pipeline_stages_init,
    pipeline_value_and_grad,
    shard_stage_params,
)

S, M, MB, D, H = 4, 6, 2, 8, 16


def _setup():
    mesh = make_mesh(devices=jax.devices()[:S], pipe=S)
    params = pipeline_stages_init(jax.random.PRNGKey(0), S, D, H)
    sharded = shard_stage_params(params, mesh)
    x = jnp.asarray(
        np.random.RandomState(1).randn(M, MB, D).astype(np.float32))
    return mesh, params, sharded, x


def _sequential(params, x):
    out = x
    for s in range(S):
        p = jax.tree_util.tree_map(lambda a, s=s: a[s], params)
        out = jax.vmap(lambda mb: dense_block_stage(p, mb))(out)
    return out


def test_pipeline_forward_matches_sequential():
    mesh, params, sharded, x = _setup()
    got = pipeline_apply(dense_block_stage, sharded, x, mesh)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    mesh, params, sharded, x = _setup()

    def loss_pipe(p):
        return jnp.sum(jnp.square(pipeline_apply(
            dense_block_stage, p, x, mesh)))

    def loss_seq(p):
        return jnp.sum(jnp.square(_sequential(p, x)))

    g_pipe = jax.grad(loss_pipe)(sharded)
    g_seq = jax.grad(loss_seq)(params)
    for k in g_seq:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(g_pipe[k])),
            np.asarray(jax.device_get(g_seq[k])),
            rtol=1e-4, atol=1e-5, err_msg=k)


def test_pipeline_jits_and_trains():
    mesh, params, sharded, x = _setup()
    y = jnp.asarray(np.random.RandomState(2).randn(M, MB, D)
                    .astype(np.float32))

    @jax.jit
    def step(p):
        def loss(p):
            out = pipeline_apply(dense_block_stage, p, x, mesh)
            return jnp.mean(jnp.square(out - y))

        l, g = jax.value_and_grad(loss)(p)
        return l, jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    l0, p2 = step(sharded)
    l1 = l0
    for _ in range(10):
        l1, p2 = step(p2)
    assert float(l1) < float(l0)


# ---------------------------------------------------------------------------
# Tick schedules (gpipe / 1f1b): analytic shape of the tables
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("schedule", SCHEDULES)
@pytest.mark.parametrize("S_,M_", [(2, 4), (4, 8), (4, 5), (4, 2), (8, 8)])
def test_schedule_tables_well_formed(schedule, S_, M_):
    sched = build_pipeline_schedule(S_, M_, schedule)
    # both schedules drain in the same 2(M+S-1) ticks; they differ only in
    # interleaving (i.e. peak resident activations), not wall-clock
    assert sched.ticks == 2 * (M_ + S_ - 1)
    for s in range(S_):
        ops = sched.ops[:, s]
        assert int((ops == 1).sum()) == M_, f"stage {s} forwards"
        assert int((ops == 2).sum()) == M_, f"stage {s} backwards"
    expected = (S_ - 1) / (M_ + S_ - 1)
    assert sched.bubble_share == pytest.approx(expected, abs=1e-12)


def test_1f1b_resident_activations_bounded_by_stages():
    # the 1F1B memory story: at most min(S, M) microbatch activations are
    # ever stashed per stage, independent of M; gpipe stashes all M
    for S_, M_ in [(2, 8), (4, 8), (4, 5), (8, 8), (4, 2)]:
        assert build_pipeline_schedule(S_, M_, "1f1b").max_inflight \
            <= min(S_, M_), (S_, M_)
        assert build_pipeline_schedule(S_, M_, "gpipe").max_inflight == M_


def test_bubble_gate_1f1b_s4_m8():
    # the bench gate: S=4, M=8, 1F1B must sit under 0.35 bubble share
    sched = build_pipeline_schedule(4, 8, "1f1b")
    assert sched.bubble_share < 0.35
    assert sched.bubble_share == pytest.approx(3 / 11)


def test_schedule_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown schedule"):
        build_pipeline_schedule(4, 8, "gpipe-2")


# ---------------------------------------------------------------------------
# pipeline_value_and_grad == sequential fold, across S, M, schedule, dtype
# ---------------------------------------------------------------------------


def _mse(out, y_mb):
    return jnp.mean(jnp.square(out - y_mb))


def _seq_value_and_grad(params, x, y, n_stages):
    def loss(p):
        tot = 0.0
        for m in range(x.shape[0]):
            out = x[m]
            for s in range(n_stages):
                ps = jax.tree_util.tree_map(lambda a, s=s: a[s], p)
                out = dense_block_stage(ps, out)
            tot = tot + _mse(out, y[m])
        return tot / x.shape[0]

    return jax.value_and_grad(loss)(params)


# one schedule per shape (not the full product): each compile is ~5 s on
# the CPU mesh and exactness is schedule-independent once both kinds are
# covered — gpipe gets the degenerate fills, 1f1b the regular shapes
@pytest.mark.parametrize("schedule,S_,M_", [
    ("1f1b", 2, 4),   # shallow pipe
    ("1f1b", 8, 8),   # whole 8-device mesh as pipe
    ("gpipe", 4, 5),  # M not a multiple of S
    ("gpipe", 4, 2),  # M < S: fill/drain dominated, still exact
])
def test_value_and_grad_matches_sequential(schedule, S_, M_):
    mesh = make_mesh(devices=jax.devices()[:S_], pipe=S_)
    params = shard_stage_params(
        pipeline_stages_init(jax.random.PRNGKey(0), S_, D, H), mesh)
    rs = np.random.RandomState(S_ * 10 + M_)
    x = jnp.asarray(rs.randn(M_, MB, D).astype(np.float32))
    y = jnp.asarray(rs.randn(M_, MB, D).astype(np.float32))
    loss, grads = pipeline_value_and_grad(
        dense_block_stage, params, x, y, _mse, mesh, schedule=schedule)
    ref_loss, ref_grads = _seq_value_and_grad(params, x, y, S_)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in ref_grads:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(grads[k])),
            np.asarray(jax.device_get(ref_grads[k])),
            rtol=1e-4, atol=1e-5, err_msg=f"{schedule} {k}")


def test_value_and_grad_bf16_parity():
    # bf16 activations ride the same ppermute/stash path; grads must agree
    # with the sequential bf16 fold (loose tolerance: bf16 has ~8 bits).
    # 1f1b only: it exercises the interleaved stash/recv slots that gpipe
    # doesn't, and fp32 exactness already covers both kinds above.
    schedule = "1f1b"
    S_, M_ = 4, 6
    mesh = make_mesh(devices=jax.devices()[:S_], pipe=S_)
    params = shard_stage_params(
        pipeline_stages_init(jax.random.PRNGKey(3), S_, D, H,
                             dtype=jnp.bfloat16), mesh)
    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(M_, MB, D)).astype(jnp.bfloat16)
    y = jnp.asarray(rs.randn(M_, MB, D)).astype(jnp.bfloat16)
    loss, grads = pipeline_value_and_grad(
        dense_block_stage, params, x, y, _mse, mesh, schedule=schedule)
    ref_loss, ref_grads = _seq_value_and_grad(params, x, y, S_)
    assert jnp.isfinite(loss)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=2e-2, atol=1e-2)
    for k in ref_grads:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(grads[k]), dtype=np.float32),
            np.asarray(jax.device_get(ref_grads[k]), dtype=np.float32),
            rtol=1e-1, atol=5e-2, err_msg=k)


# ---------------------------------------------------------------------------
# pipeline_apply dtype-safe result select (int / bool activations)
# ---------------------------------------------------------------------------


def test_pipeline_apply_int_activations():
    S_, M_ = 4, 6
    mesh = make_mesh(devices=jax.devices()[:S_], pipe=S_)
    shifts = jnp.arange(1, S_ + 1, dtype=jnp.int32)  # per-stage [S] param

    def stage(p, a):
        return a + p  # int32 stays int32 through the pipe

    x = jnp.asarray(
        np.random.RandomState(0).randint(0, 100, size=(M_, MB, D)),
        dtype=jnp.int32)
    got = pipeline_apply(stage, shifts[:, None], x, mesh)
    assert got.dtype == jnp.int32
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(x) + int(shifts.sum()))


def test_pipeline_apply_bool_activations():
    S_, M_ = 4, 6
    mesh = make_mesh(devices=jax.devices()[:S_], pipe=S_)
    flip = jnp.asarray([True, False, True, False])  # net: identity

    def stage(p, a):
        return jnp.logical_xor(a, p[0])

    x = jnp.asarray(
        np.random.RandomState(1).rand(M_, MB, D) > 0.5)
    got = pipeline_apply(stage, flip[:, None], x, mesh)
    assert got.dtype == jnp.bool_
    np.testing.assert_array_equal(np.asarray(got), np.asarray(x))


def test_pipeline_apply_rejects_wrong_leading_dim():
    mesh = make_mesh(devices=jax.devices()[:4], pipe=4)
    bad = {"W": jnp.zeros((3, D, D), jnp.float32)}
    x = jnp.zeros((6, MB, D), jnp.float32)
    with pytest.raises(ValueError, match="leading"):
        pipeline_apply(lambda p, a: a @ p["W"], bad, x, mesh)
