"""Pipeline parallelism (SURVEY §2.3 PP row — absent upstream): the GPipe
microbatch schedule over a 'pipe' mesh axis must match folding the stages
sequentially, in both the forward values and the gradients."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import (
    dense_block_stage,
    pipeline_apply,
    pipeline_stages_init,
    shard_stage_params,
)

S, M, MB, D, H = 4, 6, 2, 8, 16


def _setup():
    mesh = make_mesh(devices=jax.devices()[:S], pipe=S)
    params = pipeline_stages_init(jax.random.PRNGKey(0), S, D, H)
    sharded = shard_stage_params(params, mesh)
    x = jnp.asarray(
        np.random.RandomState(1).randn(M, MB, D).astype(np.float32))
    return mesh, params, sharded, x


def _sequential(params, x):
    out = x
    for s in range(S):
        p = jax.tree_util.tree_map(lambda a, s=s: a[s], params)
        out = jax.vmap(lambda mb: dense_block_stage(p, mb))(out)
    return out


def test_pipeline_forward_matches_sequential():
    mesh, params, sharded, x = _setup()
    got = pipeline_apply(dense_block_stage, sharded, x, mesh)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    mesh, params, sharded, x = _setup()

    def loss_pipe(p):
        return jnp.sum(jnp.square(pipeline_apply(
            dense_block_stage, p, x, mesh)))

    def loss_seq(p):
        return jnp.sum(jnp.square(_sequential(p, x)))

    g_pipe = jax.grad(loss_pipe)(sharded)
    g_seq = jax.grad(loss_seq)(params)
    for k in g_seq:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(g_pipe[k])),
            np.asarray(jax.device_get(g_seq[k])),
            rtol=1e-4, atol=1e-5, err_msg=k)


def test_pipeline_jits_and_trains():
    mesh, params, sharded, x = _setup()
    y = jnp.asarray(np.random.RandomState(2).randn(M, MB, D)
                    .astype(np.float32))

    @jax.jit
    def step(p):
        def loss(p):
            out = pipeline_apply(dense_block_stage, p, x, mesh)
            return jnp.mean(jnp.square(out - y))

        l, g = jax.value_and_grad(loss)(p)
        return l, jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    l0, p2 = step(sharded)
    l1 = l0
    for _ in range(10):
        l1, p2 = step(p2)
    assert float(l1) < float(l0)
