"""Tier-1 wiring for tools/check_pool_contract.py: the replica-pool
serving contract (README.md "Replica pools & caching") — p2c dispatch
across all replicas, per-replica fault isolation, priority-aware
shedding, cache-hit bypass, /metrics visibility — is enforced on every
test run, not just when someone remembers to run the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_pool_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_pool_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_pool_contract.main(log=lambda m: None) == 0
