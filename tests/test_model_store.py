"""ModelStore: versioning, atomic publish, checksums, retention/GC
(serving/store.py — README "Model registry & hot-swap serving")."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import (
    ChecksumMismatchError,
    ModelStore,
    VersionNotFoundError,
)


def _model(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture
def store(tmp_path):
    return ModelStore(str(tmp_path / "registry"))


def test_publish_assigns_monotonic_versions(store):
    assert store.models() == []
    e1 = store.publish("m", _model(1))
    e2 = store.publish("m", _model(2))
    assert (e1.version, e2.version) == (1, 2)
    assert [v.version for v in store.versions("m")] == [1, 2]
    assert store.models() == ["m"]
    # versions are per-name: a second model starts at v1
    assert store.publish("other", _model(3)).version == 1


def test_resolve_latest_and_pinned(store):
    store.publish("m", _model(1))
    store.publish("m", _model(2))
    assert store.resolve("m").version == 2
    assert store.resolve("m", "latest").version == 2
    assert store.resolve("m", 1).version == 1
    assert store.resolve("m", "v1").version == 1
    assert store.resolve("m", "2").version == 2
    with pytest.raises(VersionNotFoundError):
        store.resolve("m", 9)
    with pytest.raises(VersionNotFoundError):
        store.resolve("absent")


def test_load_round_trip_and_manifest(store):
    m = _model(7)
    entry = store.publish("m", m, metadata={"trained_on": "batch-42"})
    assert entry.metadata == {"trained_on": "batch-42"}
    assert entry.manifest["model_class"] == "MultiLayerNetwork"
    assert entry.manifest["size_bytes"] == os.path.getsize(entry.artifact_path)
    restored, got = store.load("m")
    assert got.version == entry.version
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(m.output(x)), atol=1e-6)


def test_checksum_corruption_detected(store):
    store.publish("m", _model(1))
    entry = store.resolve("m")
    with open(entry.artifact_path, "r+b") as f:
        f.seek(120)
        f.write(b"\xde\xad\xbe\xef")
    with pytest.raises(ChecksumMismatchError):
        store.load("m")
    # verify=False skips the integrity gate (explicit opt-out only)
    with pytest.raises(Exception):
        store.load("m", verify=False)  # zip itself is corrupt here too


def test_failed_publish_leaves_no_version(store, monkeypatch):
    store.publish("m", _model(1))

    def boom(*a, **k):
        raise RuntimeError("disk full")

    monkeypatch.setattr("deeplearning4j_tpu.serving.store.write_model", boom)
    with pytest.raises(RuntimeError):
        store.publish("m", _model(2))
    monkeypatch.undo()
    assert [v.version for v in store.versions("m")] == [1]
    # no staging debris either
    assert all(not d.startswith(".staging-")
               for d in os.listdir(os.path.join(store.root, "m")))
    # and the next publish still gets the next id
    assert store.publish("m", _model(2)).version == 2


def test_gc_retention_and_in_use_protection(store):
    for seed in range(5):
        store.publish("m", _model(seed))
    removed = store.gc("m", keep_last=2, in_use=[1])
    # keeps v4, v5 (newest two) and v1 (in use); removes v2, v3
    assert removed == {"m": [2, 3]}
    assert [v.version for v in store.versions("m")] == [1, 4, 5]
    # latest is never collected even with keep_last=0
    store.gc("m", keep_last=0, in_use=[])
    assert [v.version for v in store.versions("m")] == [5]


def test_gc_sweeps_stale_staging_dirs(store):
    store.publish("m", _model(1))
    stale = os.path.join(store.root, "m", ".staging-crashed")
    os.makedirs(stale)
    store.gc("m")
    assert not os.path.exists(stale)
    assert [v.version for v in store.versions("m")] == [1]


def test_store_level_default_retention(tmp_path):
    store = ModelStore(str(tmp_path), keep_last=1)
    store.publish("m", _model(1))
    store.publish("m", _model(2))
    assert store.gc() == {"m": [1]}
    assert [v.version for v in store.versions("m")] == [2]


def test_invalid_model_names_rejected(store):
    from deeplearning4j_tpu.serving import ModelStoreError

    for bad in ("", "a/b", ".hidden"):
        with pytest.raises(ModelStoreError):
            store.publish(bad, _model(1))
