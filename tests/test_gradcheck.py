"""Numerical gradient checks for the layer zoo.

Mirrors the reference's gradientcheck test family (GradientCheckTests,
CNNGradientCheckTest, LSTMGradientCheckTests, ...): small double-precision
networks, central-difference vs analytic gradients (SURVEY.md §4).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    Activation,
    InputType,
    LossFunction,
    MultiLayerNetwork,
    NeuralNetConfiguration,
    WeightInit,
)
from deeplearning4j_tpu.nn.layers import (
    BatchNormalizationLayer,
    BidirectionalLayer,
    Convolution1DLayer,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesLSTMLayer,
    LSTMLayer,
    LastTimeStepLayer,
    LayerNormLayer,
    OutputLayer,
    PoolingType,
    RnnOutputLayer,
    SelfAttentionLayer,
    SimpleRnnLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.utils import check_gradients

SEED = 42


def build(layers, input_type, l1=None, l2=None):
    b = NeuralNetConfiguration.builder().seed(SEED).data_type("float64")
    if l1 is not None:
        b = b.l1(l1)
    if l2 is not None:
        b = b.l2(l2)
    lb = b.list()
    for l in layers:
        lb = lb.layer(l)
    conf = lb.set_input_type(input_type).build()
    return MultiLayerNetwork(conf).init()


def rand(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape)


def onehot(cls, k):
    return np.eye(k)[cls]


class TestDenseGradients:
    def test_mlp_mcxent(self):
        model = build(
            [DenseLayer(n_out=6, activation=Activation.TANH),
             OutputLayer(n_out=3, loss=LossFunction.MCXENT)],
            InputType.feed_forward(4),
        )
        x = rand((5, 4))
        y = onehot(np.arange(5) % 3, 3)
        assert check_gradients(model, x, y, print_results=True)

    def test_mlp_mse_identity(self):
        model = build(
            [DenseLayer(n_out=6, activation=Activation.SIGMOID),
             OutputLayer(n_out=2, loss=LossFunction.MSE, activation=Activation.IDENTITY)],
            InputType.feed_forward(4),
        )
        x = rand((5, 4))
        y = rand((5, 2), seed=1)
        assert check_gradients(model, x, y)

    @pytest.mark.parametrize("act", [Activation.RELU, Activation.ELU, Activation.SOFTPLUS,
                                     Activation.GELU, Activation.SWISH, Activation.MISH])
    def test_activations(self, act):
        model = build(
            [DenseLayer(n_out=5, activation=act),
             OutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.feed_forward(3),
        )
        x = rand((4, 3), seed=2) + 0.1  # avoid relu kinks at 0
        y = onehot(np.arange(4) % 2, 2)
        assert check_gradients(model, x, y)

    def test_l1_l2_regularization(self):
        model = build(
            [DenseLayer(n_out=5, activation=Activation.TANH),
             OutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.feed_forward(3), l1=1e-2, l2=1e-2,
        )
        x = rand((4, 3), seed=3)
        y = onehot(np.arange(4) % 2, 2)
        assert check_gradients(model, x, y)

    def test_embedding(self):
        model = build(
            [EmbeddingLayer(n_in=7, n_out=5, activation=Activation.TANH),
             OutputLayer(n_out=3, loss=LossFunction.MCXENT)],
            InputType.feed_forward(1),
        )
        x = (np.arange(6) % 7).reshape(6, 1).astype(np.float64)
        y = onehot(np.arange(6) % 3, 3)
        assert check_gradients(model, x, y)

    def test_layernorm(self):
        model = build(
            [DenseLayer(n_out=6, activation=Activation.IDENTITY),
             LayerNormLayer(),
             OutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.feed_forward(4),
        )
        x = rand((5, 4), seed=4)
        y = onehot(np.arange(5) % 2, 2)
        assert check_gradients(model, x, y)


class TestCnnGradients:
    def test_conv_pool_dense(self):
        model = build(
            [ConvolutionLayer(n_out=3, kernel_size=(2, 2), activation=Activation.TANH),
             SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
             OutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.convolutional(6, 6, 2),
        )
        x = rand((3, 2, 6, 6), seed=5)
        y = onehot(np.arange(3) % 2, 2)
        assert check_gradients(model, x, y, subset=150)

    def test_avg_pool(self):
        model = build(
            [ConvolutionLayer(n_out=2, kernel_size=(3, 3), activation=Activation.SIGMOID),
             SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), pooling_type=PoolingType.AVG),
             OutputLayer(n_out=2, loss=LossFunction.MSE, activation=Activation.IDENTITY)],
            InputType.convolutional(6, 6, 1),
        )
        x = rand((3, 1, 6, 6), seed=6)
        y = rand((3, 2), seed=7)
        assert check_gradients(model, x, y, subset=120)

    def test_batchnorm(self):
        model = build(
            [ConvolutionLayer(n_out=2, kernel_size=(2, 2), activation=Activation.IDENTITY),
             BatchNormalizationLayer(),
             GlobalPoolingLayer(pooling_type=PoolingType.AVG),
             OutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.convolutional(5, 5, 1),
        )
        x = rand((4, 1, 5, 5), seed=8)
        y = onehot(np.arange(4) % 2, 2)
        assert check_gradients(model, x, y, subset=120)

    def test_conv1d(self):
        model = build(
            [Convolution1DLayer(n_out=3, kernel_size=2, activation=Activation.TANH),
             RnnOutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.recurrent(2, 7),
        )
        x = rand((3, 2, 7), seed=9)
        cls = (rand((3, 6), seed=10) > 0).astype(int)
        y = np.eye(2)[cls].transpose(0, 2, 1)
        assert check_gradients(model, x, y)


class TestRnnGradients:
    def test_lstm(self):
        model = build(
            [LSTMLayer(n_out=4, activation=Activation.TANH),
             RnnOutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.recurrent(3),
        )
        x = rand((3, 3, 5), seed=11)
        cls = (rand((3, 5), seed=12) > 0).astype(int)
        y = np.eye(2)[cls].transpose(0, 2, 1)
        assert check_gradients(model, x, y)

    def test_graves_lstm_peepholes(self):
        model = build(
            [GravesLSTMLayer(n_out=3, activation=Activation.TANH),
             RnnOutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.recurrent(2),
        )
        x = rand((2, 2, 4), seed=13)
        cls = (rand((2, 4), seed=14) > 0).astype(int)
        y = np.eye(2)[cls].transpose(0, 2, 1)
        assert check_gradients(model, x, y)

    def test_lstm_with_mask(self):
        model = build(
            [LSTMLayer(n_out=3),
             RnnOutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.recurrent(2),
        )
        x = rand((3, 2, 6), seed=15)
        cls = (rand((3, 6), seed=16) > 0).astype(int)
        y = np.eye(2)[cls].transpose(0, 2, 1)
        mask = np.ones((3, 6))
        mask[0, 4:] = 0
        mask[2, 2:] = 0
        assert check_gradients(model, x, y, mask=mask, label_mask=mask)

    def test_simple_rnn(self):
        model = build(
            [SimpleRnnLayer(n_out=4),
             RnnOutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.recurrent(3),
        )
        x = rand((3, 3, 5), seed=17)
        cls = (rand((3, 5), seed=18) > 0).astype(int)
        y = np.eye(2)[cls].transpose(0, 2, 1)
        assert check_gradients(model, x, y)

    def test_bidirectional_lstm(self):
        model = build(
            [BidirectionalLayer(fwd=LSTMLayer(n_out=3)),
             RnnOutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.recurrent(2),
        )
        x = rand((2, 2, 5), seed=19)
        cls = (rand((2, 5), seed=20) > 0).astype(int)
        y = np.eye(2)[cls].transpose(0, 2, 1)
        assert check_gradients(model, x, y)

    def test_last_time_step(self):
        model = build(
            [LastTimeStepLayer(underlying=LSTMLayer(n_out=4)),
             OutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.recurrent(3),
        )
        x = rand((3, 3, 6), seed=21)
        y = onehot(np.arange(3) % 2, 2)
        assert check_gradients(model, x, y)

    def test_self_attention(self):
        model = build(
            [SelfAttentionLayer(n_out=4, n_heads=2, activation=Activation.IDENTITY),
             GlobalPoolingLayer(pooling_type=PoolingType.AVG),
             OutputLayer(n_out=2, loss=LossFunction.MCXENT)],
            InputType.recurrent(4),
        )
        x = rand((3, 4, 5), seed=22)
        y = onehot(np.arange(3) % 2, 2)
        assert check_gradients(model, x, y)
