"""Coverage-gap components (VERDICT.md round 3 missing 6-9): dataset
fetchers (CIFAR-10/EMNIST shapes), GloVe, ParagraphVectors, the
SameDiffLayer escape hatch, and A3C."""

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# fetchers
# ---------------------------------------------------------------------------

def test_cifar10_iterator_shapes_and_determinism():
    from deeplearning4j_tpu.data.fetchers import Cifar10DataSetIterator

    it = Cifar10DataSetIterator(32, train=True, num_examples=128, shuffle=False)
    ds = next(iter(it))
    assert ds.features.shape == (32, 3, 32, 32)
    assert ds.labels.shape == (32, 10)
    assert 0.0 <= float(np.min(ds.features)) and float(np.max(ds.features)) <= 1.0
    it2 = Cifar10DataSetIterator(32, train=True, num_examples=128, shuffle=False)
    np.testing.assert_array_equal(ds.features, next(iter(it2)).features)
    # test split differs from train
    te = next(iter(Cifar10DataSetIterator(32, train=False, num_examples=64,
                                          shuffle=False)))
    assert not np.array_equal(ds.features[:32], te.features[:32])


def test_cifar10_is_learnable():
    from deeplearning4j_tpu.data.fetchers import Cifar10DataSetIterator
    from deeplearning4j_tpu.nn import (
        Activation, InputType, LossFunction, NeuralNetConfiguration, WeightInit,
    )
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, GlobalPoolingLayer, OutputLayer, PoolingType,
    )
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.train.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(3e-3))
            .weight_init(WeightInit.XAVIER).list()
            .layer(ConvolutionLayer(n_out=12, kernel_size=(3, 3),
                                    activation=Activation.RELU))
            .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
            .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional(32, 32, 3)).build())
    net = MultiLayerNetwork(conf).init()
    it = Cifar10DataSetIterator(64, train=True, num_examples=512)
    net.fit(it, epochs=20)
    ev = net.evaluate(Cifar10DataSetIterator(64, train=True, num_examples=512,
                                             shuffle=False))
    assert ev.accuracy() > 0.35  # 10-class chance is 0.1


def test_emnist_splits():
    from deeplearning4j_tpu.data.fetchers import EmnistDataSetIterator

    it = EmnistDataSetIterator("letters", 16, num_examples=64)
    ds = next(iter(it))
    assert ds.features.shape == (16, 784)
    assert ds.labels.shape == (16, 26)
    it2 = EmnistDataSetIterator("balanced", 8, num_examples=32)
    assert next(iter(it2)).labels.shape == (8, 47)
    with pytest.raises(ValueError, match="unknown EMNIST split"):
        EmnistDataSetIterator("nope", 8)


# ---------------------------------------------------------------------------
# GloVe / ParagraphVectors
# ---------------------------------------------------------------------------

def _corpus(n=300, seed=0):
    """Two topic clusters; co-occurrence should pull topic words together."""
    rng = np.random.RandomState(seed)
    animals = ["cat", "dog", "horse", "sheep", "goat"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk"]
    sents = []
    for _ in range(n):
        pool = animals if rng.rand() < 0.5 else tech
        sents.append([pool[rng.randint(5)] for _ in range(rng.randint(4, 9))])
    return sents, animals, tech


def test_glove_trains_and_clusters():
    from deeplearning4j_tpu.nlp import Glove

    sents, animals, tech = _corpus()
    g = Glove(vector_size=16, window=3, min_count=1, epochs=12,
              batch_size=256, seed=1)
    g.fit(sents)
    assert g.has_word("cat") and g.get_word_vector("cat").shape == (16,)
    within = np.mean([g.similarity("cat", w) for w in animals if w != "cat"])
    across = np.mean([g.similarity("cat", w) for w in tech])
    assert within > across, f"within={within:.3f} across={across:.3f}"
    assert "cat" not in g.words_nearest("cat", 3)


def test_paragraph_vectors_fit_and_infer():
    from deeplearning4j_tpu.nlp import LabelledDocument, ParagraphVectors

    sents, animals, tech = _corpus(200)
    docs = [LabelledDocument(s, f"doc_{i}") for i, s in enumerate(sents)]
    pv = ParagraphVectors(vector_size=16, min_count=1, epochs=60,
                          learning_rate=5.0, batch_size=256, seed=2)
    pv.fit(docs)
    assert pv.get_doc_vector("doc_0").shape == (16,)
    # an inferred vector for an animal-topic doc should land nearer animal
    # docs than tech docs on average
    vec = pv.infer_vector(["cat", "dog", "horse", "cat"])
    assert vec.shape == (16,) and np.isfinite(vec).all()
    near = pv.nearest_labels(["cat", "dog", "horse", "cat"], n=10)
    animal_docs = {f"doc_{i}" for i, s in enumerate(sents)
                   if s[0] in animals}
    hits = sum(1 for l in near if l in animal_docs)
    assert hits >= 6, f"only {hits}/10 nearest docs share the topic"
    assert "doc_0" not in pv.nearest_labels("doc_0", 3)


# ---------------------------------------------------------------------------
# SameDiffLayer escape hatch
# ---------------------------------------------------------------------------

def test_samediff_lambda_layer_in_sequential():
    from deeplearning4j_tpu.nn import (
        Activation, InputType, LossFunction, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import (
        DenseLayer, OutputLayer, SameDiffLambdaLayer,
    )
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.train.updaters import Sgd

    def double_it(sd, x):  # SameDiff-graph spelling
        return sd._op("mul", x, sd.constant(np.float32(2.0)))

    conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(SameDiffLambdaLayer(fn=double_it))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 4)]
    out = np.asarray(net.output(x))
    assert out.shape == (4, 3)
    losses = [float(net.fit(x, y, epochs=1).score_value) for _ in range(15)]
    assert losses[-1] < losses[0]  # trains THROUGH the custom op


def test_samediff_layer_with_params_gradient_flow():
    from deeplearning4j_tpu.nn import (
        Activation, InputType, LossFunction, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import OutputLayer, SameDiffLayer
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.train.updaters import Sgd

    def custom_dense(sd, x, params):  # reference defineLayer idiom
        y = sd._op("matmul", x, params["W"])
        return sd._op("tanh", sd._op("add", y, params["b"]))

    layer = SameDiffLayer(
        param_shapes={"W": (5, 7), "b": (7,)},
        define_layer=custom_dense, n_out=7)
    conf = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.2)).list()
            .layer(layer)
            .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    assert net.params["layer_0"]["W"].shape == (5, 7)
    w_before = np.asarray(net.params["layer_0"]["W"]).copy()
    rng = np.random.RandomState(1)
    x = rng.randn(8, 5).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
    for _ in range(10):
        net.fit(x, y, epochs=1)
    assert not np.allclose(w_before, np.asarray(net.params["layer_0"]["W"]))


def test_samediff_lambda_plain_jnp_spelling():
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn import (
        Activation, InputType, LossFunction, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import OutputLayer, SameDiffLambdaLayer
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(5).list()
            .layer(SameDiffLambdaLayer(fn=lambda x: jnp.tanh(x) * 3.0))
            .layer(OutputLayer(n_out=2, loss=LossFunction.MSE,
                               activation=Activation.IDENTITY))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    out = net.output(np.ones((2, 4), np.float32))
    assert out.shape == (2, 2)


# ---------------------------------------------------------------------------
# A3C
# ---------------------------------------------------------------------------

def test_a3c_cartpole_improves():
    from deeplearning4j_tpu.rl import A3CConfiguration, A3CDiscreteDense, CartPole

    conf = A3CConfiguration(seed=7, num_threads=8, n_step=16,
                            max_step=16000, learning_rate=1e-3,
                            entropy_coef=0.01, hidden=(32, 32))
    a3c = A3CDiscreteDense(lambda: CartPole(max_steps=200, seed=7), conf)
    a3c.train()
    rewards = np.asarray(a3c.episode_rewards)
    assert len(rewards) >= 10
    # RL learning curves are noisy; assert the robust signals: the second
    # half of training out-earns the first, and peak episodes far exceed
    # the untrained baseline (~14 steps on this seed)
    half = len(rewards) // 2
    assert rewards[half:].mean() > rewards[:half].mean(), (
        f"no improvement: {rewards[:half].mean():.1f} -> "
        f"{rewards[half:].mean():.1f}")
    assert np.sort(rewards)[-10:].mean() > 45, (
        f"best episodes never took off: {np.sort(rewards)[-10:].mean():.1f}")
    policy = a3c.get_policy()
    assert policy.next_action(CartPole(seed=1).reset()) in (0, 1)


def test_word_vector_serializer_roundtrip(tmp_path):
    from deeplearning4j_tpu.nlp import Glove, WordVectorSerializer

    sents, animals, tech = _corpus(100)
    g = Glove(vector_size=8, window=3, min_count=1, epochs=3, seed=4)
    g.fit(sents)
    path = str(tmp_path / "vectors.txt")
    WordVectorSerializer.write_word_vectors(g, path)
    wv = WordVectorSerializer.read_word_vectors(path)
    assert wv.vocab == g.vocab
    np.testing.assert_allclose(wv.get_word_vector("cat"),
                               g.get_word_vector("cat"), rtol=1e-4, atol=1e-5)
    # query API carried over
    assert wv.similarity("cat", "dog") == pytest.approx(
        g.similarity("cat", "dog"), abs=1e-4)
    assert len(wv.words_nearest("cat", 3)) == 3


def test_round4_component_inventory():
    """Pin the round-4 additions so coverage regressions fail loudly:
    every SURVEY §2/§2.3/§5 row landed this round must stay importable
    with its public surface intact."""
    # parallelism: all four modes + multi-process machinery
    from deeplearning4j_tpu.parallel import (
        DistributedTrainer, ParallelInference, dense_block_stage,
        make_mesh, pipeline_apply, pipeline_stages_init, ring_attention,
        shard_stage_params, ulysses_attention,
    )
    from deeplearning4j_tpu.nn.layers import MixtureOfExpertsLayer
    # checkpoint/resume: both the parity path and the orbax path
    from deeplearning4j_tpu.train import OrbaxCheckpointer
    from deeplearning4j_tpu.train.fault_tolerance import Watchdog
    # UI: storage + web server
    from deeplearning4j_tpu.ui import InMemoryStatsStorage, UIServer
    # zoo completeness (the reference's full architecture list)
    from deeplearning4j_tpu.model.zoo import NASNet
    # fetchers (SURVEY §2.2 "Dataset fetchers" full family)
    from deeplearning4j_tpu.data import (
        Cifar10DataSetIterator, EmnistDataSetIterator, SvhnDataSetIterator,
        TinyImageNetDataSetIterator,
    )
    # import breadth floors (tranche-3 widening must not shrink)
    from deeplearning4j_tpu.modelimport.onnx import ONNX_OP_RULES
    from deeplearning4j_tpu.modelimport.keras import (
        register_keras_custom_layer, register_keras_lambda,
    )
    from deeplearning4j_tpu.samediff.ops import SD_OPS
    from deeplearning4j_tpu.samediff.tf_import import TF_OP_RULES

    assert len(SD_OPS) >= 500, f"op registry shrank: {len(SD_OPS)}"
    assert len(TF_OP_RULES) >= 220, f"TF rules shrank: {len(TF_OP_RULES)}"
    assert len(ONNX_OP_RULES) >= 120, f"ONNX rules shrank: {len(ONNX_OP_RULES)}"
