"""Tier-1 wiring for tools/check_registry_contract.py: the model-registry
lifecycle contract (publish → resolve → serve → swap → rollback → gc,
README.md "Model registry & hot-swap serving") is enforced on every test
run, not just when someone remembers to run the tool."""

import os
import sys

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


def test_registry_contract_smoke():
    sys.path.insert(0, _TOOLS)
    try:
        import check_registry_contract
    finally:
        sys.path.remove(_TOOLS)
    assert check_registry_contract.main(log=lambda m: None) == 0
