"""Failure detection + elastic restart (SURVEY.md §5.3): the supervisor
must resume training from the latest checkpoint after a crash, and the
watchdog must detect a stalled (wedged-device-shaped) child."""

import os
import textwrap
import time

import pytest

from deeplearning4j_tpu.train.fault_tolerance import (
    HeartbeatListener,
    Watchdog,
    elastic_fit,
    read_heartbeat,
)


def test_heartbeat_listener_writes_progress(tmp_path):
    hb = HeartbeatListener(str(tmp_path))

    class FakeModel:
        pass

    hb.iteration_done(FakeModel(), 7, 1, 0.5)
    got = read_heartbeat(str(tmp_path))
    assert got["iteration"] == 7 and got["epoch"] == 1
    assert got["score"] == pytest.approx(0.5)


def test_watchdog_fires_on_stall(tmp_path):
    fired = []
    wd = Watchdog(str(tmp_path), timeout=0.3, poll_interval=0.05,
                  on_stall=lambda: fired.append(True))
    wd.start()
    time.sleep(1.0)
    wd.stop()
    assert fired  # no heartbeat ever arrived -> stall


def test_watchdog_quiet_while_progressing(tmp_path):
    fired = []
    wd = Watchdog(str(tmp_path), timeout=0.5, poll_interval=0.05,
                  on_stall=lambda: fired.append(True))
    hb = HeartbeatListener(str(tmp_path))
    wd.start()
    for i in range(6):
        hb.iteration_done(None, i, 0, 0.1)
        time.sleep(0.15)
    wd.stop()
    time.sleep(0.2)
    assert not fired


_ENTRY = textwrap.dedent('''
    """Elastic-fit test target: crashes mid-training on the first run
    (marker file absent), completes on the resume run."""
    import os

    import numpy as np


    def train(resume_path, checkpoint_dir):
        import jax
        jax.config.update("jax_platforms", "cpu")

        from deeplearning4j_tpu.model.serializer import restore_model
        from deeplearning4j_tpu.nn import (
            Activation, InputType, LossFunction, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener
        from deeplearning4j_tpu.train.fault_tolerance import HeartbeatListener
        from deeplearning4j_tpu.train.updaters import Sgd

        if resume_path:
            model = restore_model(resume_path, load_updater=True)
        else:
            conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1))
                    .list()
                    .layer(DenseLayer(n_out=8, activation=Activation.TANH))
                    .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                                       activation=Activation.SOFTMAX))
                    .set_input_type(InputType.feed_forward(4)).build())
            model = MultiLayerNetwork(conf).init()
        model.add_listeners(
            CheckpointListener(checkpoint_dir, save_every_n_iterations=5),
            HeartbeatListener(checkpoint_dir))

        rng = np.random.RandomState(0)
        x = rng.rand(8, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
        crash_marker = os.path.join(checkpoint_dir, "crashed_once")
        target_iters = 30
        while model.iteration_count < target_iters:
            model.fit(x, y, epochs=1)
            if model.iteration_count >= 12 and not os.path.exists(crash_marker):
                open(crash_marker, "w").write("boom")
                os._exit(1)  # simulated worker death mid-training
''')


def test_elastic_fit_resumes_after_crash(tmp_path):
    from deeplearning4j_tpu.core.resilience import RetryPolicy

    target = tmp_path / "elastic_target.py"
    target.write_text(_ENTRY)
    ckpt = str(tmp_path / "ckpt")
    result = elastic_fit(
        "elastic_target:train", ckpt, max_restarts=2, stall_timeout=120.0,
        retry_policy=RetryPolicy(max_retries=2, initial_backoff=0.01),
        env={"PYTHONPATH": str(tmp_path) + os.pathsep
             + os.environ.get("PYTHONPATH", ""),
             "JAX_PLATFORMS": "cpu"},
        log_fn=lambda m: None)
    assert result["ok"], result
    assert result["restarts"] == 1  # crashed once, resumed, completed
    kinds = [e["event"] for e in result["events"]]
    assert kinds == ["crash", "backoff", "completed"]
    # the resumed run really continued past the crash point
    hb = read_heartbeat(ckpt)
    assert hb["iteration"] >= 30
    # and it resumed FROM the checkpoint (crash at >=12, checkpoints every 5)
    assert result["events"][0]["last_heartbeat"]["iteration"] >= 10


class TestElasticRestartDiscipline:
    """Restart backoff + crash-loop detection, fully deterministic: the
    child is a ``spawn_fn`` stub, the clock is fake, sleeps are recorded.
    No subprocesses, no wall-clock waits."""

    @staticmethod
    def _clock_sleep():
        t = [0.0]
        slept = []

        def clock():
            return t[0]

        def sleep(dt):
            slept.append(dt)
            t[0] += dt

        return t, slept, clock, sleep

    def test_backoff_between_restarts_is_exponential(self, tmp_path):
        from deeplearning4j_tpu.core.resilience import RetryPolicy

        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=3,
            retry_policy=RetryPolicy(max_retries=3, initial_backoff=1.0,
                                     multiplier=2.0, jitter=0.0),
            crash_loop_window=0.0,      # window disabled: nothing ever counts
            spawn_fn=lambda: 1, sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        assert not result["ok"]
        assert result["events"][-1]["event"] == "gave_up"
        assert slept == [1.0, 2.0, 4.0]

    def test_crash_loop_gives_up_before_max_restarts(self, tmp_path):
        spawns = []
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=50,
            crash_loop_window=600.0, crash_loop_budget=3,
            spawn_fn=lambda: spawns.append(1) or 1, sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        assert not result["ok"]
        assert result["events"][-1]["event"] == "crash_loop"
        assert result["restarts"] == 3      # budget, nowhere near 50
        assert len(spawns) == 4             # initial + 3 restarts

    def test_slow_failures_outside_window_use_full_budget(self, tmp_path):
        t, _, clock, _ = self._clock_sleep()

        def slow_sleep(dt):  # each restart lands outside the loop window
            t[0] += 1000.0

        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=4,
            crash_loop_window=600.0, crash_loop_budget=2,
            spawn_fn=lambda: 1, sleep=slow_sleep, clock=clock,
            log_fn=lambda m: None)
        assert not result["ok"]
        # failures were spread out -> no crash loop, the full restart
        # budget was spent before giving up
        assert result["events"][-1]["event"] == "gave_up"
        assert result["restarts"] == 4

    def test_recovery_after_transient_crashes(self, tmp_path):
        rcs = iter([1, 86, 0])  # crash, stall, then success
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=5,
            spawn_fn=lambda: next(rcs), sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        assert result["ok"] and result["restarts"] == 2
        kinds = [e["event"] for e in result["events"]]
        assert kinds == ["crash", "backoff", "stall", "backoff", "completed"]
        assert len(slept) == 2

    def test_fault_injector_spawn_site_is_live(self, tmp_path):
        from deeplearning4j_tpu.core.resilience import (
            FaultInjector, set_fault_injector)

        inj = FaultInjector()
        inj.inject_error("elastic_fit.spawn",
                         lambda: RuntimeError("injected supervisor fault"),
                         times=1)
        prev = set_fault_injector(inj)
        try:
            with pytest.raises(RuntimeError, match="injected supervisor"):
                elastic_fit("unused:train", str(tmp_path),
                            spawn_fn=lambda: 0, log_fn=lambda m: None)
        finally:
            set_fault_injector(prev)
        assert inj.fired("elastic_fit.spawn") == 1
        # with the plan exhausted the supervisor runs normally
        result = elastic_fit("unused:train", str(tmp_path),
                             spawn_fn=lambda: 0, log_fn=lambda m: None)
        assert result["ok"]


def test_watchdog_ignores_stale_heartbeat_on_restart(tmp_path):
    """Regression: a restarted child inherits the previous run's old
    heartbeat file — it must still get the full grace period."""
    hb = HeartbeatListener(str(tmp_path))
    hb.iteration_done(None, 5, 0, 0.1)
    # age the heartbeat far past the timeout
    path = os.path.join(str(tmp_path), "heartbeat.json")
    import json as _json
    with open(path) as f:
        data = _json.load(f)
    data["ts"] -= 100.0
    with open(path, "w") as f:
        _json.dump(data, f)

    fired = []
    wd = Watchdog(str(tmp_path), timeout=0.6, poll_interval=0.05,
                  on_stall=lambda: fired.append(True))
    wd.start()
    time.sleep(0.3)
    assert not fired  # grace period counted from start(), not the stale ts
    time.sleep(0.6)
    wd.stop()
    assert fired  # and it still fires once the REAL grace period lapses
