"""Failure detection + elastic restart (SURVEY.md §5.3): the supervisor
must resume training from the latest checkpoint after a crash, and the
watchdog must detect a stalled (wedged-device-shaped) child. ISSUE 15
adds: preemption-aware stop (PREEMPTED_EXIT_CODE classification — no
backoff, no crash budget), async checkpointing semantics (bounded
writer, crash-consistent pointer, fault tolerance, keep_last across
restarts, decoupled triggers), and the Watchdog stop() race fix."""

import os
import textwrap
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.train.fault_tolerance import (
    PREEMPTED_EXIT_CODE,
    HeartbeatListener,
    PreemptionHandler,
    Watchdog,
    elastic_fit,
    read_heartbeat,
)


def test_heartbeat_listener_writes_progress(tmp_path):
    hb = HeartbeatListener(str(tmp_path))

    class FakeModel:
        pass

    hb.iteration_done(FakeModel(), 7, 1, 0.5)
    got = read_heartbeat(str(tmp_path))
    assert got["iteration"] == 7 and got["epoch"] == 1
    assert got["score"] == pytest.approx(0.5)


def test_watchdog_fires_on_stall(tmp_path):
    fired = []
    wd = Watchdog(str(tmp_path), timeout=0.3, poll_interval=0.05,
                  on_stall=lambda: fired.append(True))
    wd.start()
    time.sleep(1.0)
    wd.stop()
    assert fired  # no heartbeat ever arrived -> stall


def test_watchdog_quiet_while_progressing(tmp_path):
    fired = []
    wd = Watchdog(str(tmp_path), timeout=0.5, poll_interval=0.05,
                  on_stall=lambda: fired.append(True))
    hb = HeartbeatListener(str(tmp_path))
    wd.start()
    for i in range(6):
        hb.iteration_done(None, i, 0, 0.1)
        time.sleep(0.15)
    wd.stop()
    time.sleep(0.2)
    assert not fired


_ENTRY = textwrap.dedent('''
    """Elastic-fit test target: crashes mid-training on the first run
    (marker file absent), completes on the resume run."""
    import os

    import numpy as np


    def train(resume_path, checkpoint_dir):
        import jax
        jax.config.update("jax_platforms", "cpu")

        from deeplearning4j_tpu.model.serializer import restore_model
        from deeplearning4j_tpu.nn import (
            Activation, InputType, LossFunction, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener
        from deeplearning4j_tpu.train.fault_tolerance import HeartbeatListener
        from deeplearning4j_tpu.train.updaters import Sgd

        if resume_path:
            model = restore_model(resume_path, load_updater=True)
        else:
            conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1))
                    .list()
                    .layer(DenseLayer(n_out=8, activation=Activation.TANH))
                    .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                                       activation=Activation.SOFTMAX))
                    .set_input_type(InputType.feed_forward(4)).build())
            model = MultiLayerNetwork(conf).init()
        model.add_listeners(
            CheckpointListener(checkpoint_dir, save_every_n_iterations=5),
            HeartbeatListener(checkpoint_dir))

        rng = np.random.RandomState(0)
        x = rng.rand(8, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
        crash_marker = os.path.join(checkpoint_dir, "crashed_once")
        target_iters = 30
        while model.iteration_count < target_iters:
            model.fit(x, y, epochs=1)
            if model.iteration_count >= 12 and not os.path.exists(crash_marker):
                open(crash_marker, "w").write("boom")
                os._exit(1)  # simulated worker death mid-training
''')


def test_elastic_fit_resumes_after_crash(tmp_path):
    from deeplearning4j_tpu.core.resilience import RetryPolicy

    target = tmp_path / "elastic_target.py"
    target.write_text(_ENTRY)
    ckpt = str(tmp_path / "ckpt")
    result = elastic_fit(
        "elastic_target:train", ckpt, max_restarts=2, stall_timeout=120.0,
        retry_policy=RetryPolicy(max_retries=2, initial_backoff=0.01),
        env={"PYTHONPATH": str(tmp_path) + os.pathsep
             + os.environ.get("PYTHONPATH", ""),
             "JAX_PLATFORMS": "cpu"},
        log_fn=lambda m: None)
    assert result["ok"], result
    assert result["restarts"] == 1  # crashed once, resumed, completed
    kinds = [e["event"] for e in result["events"]]
    assert kinds == ["crash", "backoff", "completed"]
    # the resumed run really continued past the crash point
    hb = read_heartbeat(ckpt)
    assert hb["iteration"] >= 30
    # and it resumed FROM the checkpoint (crash at >=12, checkpoints every 5)
    assert result["events"][0]["last_heartbeat"]["iteration"] >= 10


class TestElasticRestartDiscipline:
    """Restart backoff + crash-loop detection, fully deterministic: the
    child is a ``spawn_fn`` stub, the clock is fake, sleeps are recorded.
    No subprocesses, no wall-clock waits."""

    @staticmethod
    def _clock_sleep():
        t = [0.0]
        slept = []

        def clock():
            return t[0]

        def sleep(dt):
            slept.append(dt)
            t[0] += dt

        return t, slept, clock, sleep

    def test_backoff_between_restarts_is_exponential(self, tmp_path):
        from deeplearning4j_tpu.core.resilience import RetryPolicy

        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=3,
            retry_policy=RetryPolicy(max_retries=3, initial_backoff=1.0,
                                     multiplier=2.0, jitter=0.0),
            crash_loop_window=0.0,      # window disabled: nothing ever counts
            spawn_fn=lambda: 1, sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        assert not result["ok"]
        assert result["events"][-1]["event"] == "gave_up"
        assert slept == [1.0, 2.0, 4.0]

    def test_crash_loop_gives_up_before_max_restarts(self, tmp_path):
        spawns = []
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=50,
            crash_loop_window=600.0, crash_loop_budget=3,
            spawn_fn=lambda: spawns.append(1) or 1, sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        assert not result["ok"]
        assert result["events"][-1]["event"] == "crash_loop"
        assert result["restarts"] == 3      # budget, nowhere near 50
        assert len(spawns) == 4             # initial + 3 restarts

    def test_slow_failures_outside_window_use_full_budget(self, tmp_path):
        t, _, clock, _ = self._clock_sleep()

        def slow_sleep(dt):  # each restart lands outside the loop window
            t[0] += 1000.0

        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=4,
            crash_loop_window=600.0, crash_loop_budget=2,
            spawn_fn=lambda: 1, sleep=slow_sleep, clock=clock,
            log_fn=lambda m: None)
        assert not result["ok"]
        # failures were spread out -> no crash loop, the full restart
        # budget was spent before giving up
        assert result["events"][-1]["event"] == "gave_up"
        assert result["restarts"] == 4

    def test_recovery_after_transient_crashes(self, tmp_path):
        rcs = iter([1, 86, 0])  # crash, stall, then success
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=5,
            spawn_fn=lambda: next(rcs), sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        assert result["ok"] and result["restarts"] == 2
        kinds = [e["event"] for e in result["events"]]
        assert kinds == ["crash", "backoff", "stall", "backoff", "completed"]
        assert len(slept) == 2

    def test_fault_injector_spawn_site_is_live(self, tmp_path):
        from deeplearning4j_tpu.core.resilience import (
            FaultInjector, set_fault_injector)

        inj = FaultInjector()
        inj.inject_error("elastic_fit.spawn",
                         lambda: RuntimeError("injected supervisor fault"),
                         times=1)
        prev = set_fault_injector(inj)
        try:
            with pytest.raises(RuntimeError, match="injected supervisor"):
                elastic_fit("unused:train", str(tmp_path),
                            spawn_fn=lambda: 0, log_fn=lambda m: None)
        finally:
            set_fault_injector(prev)
        assert inj.fired("elastic_fit.spawn") == 1
        # with the plan exhausted the supervisor runs normally
        result = elastic_fit("unused:train", str(tmp_path),
                             spawn_fn=lambda: 0, log_fn=lambda m: None)
        assert result["ok"]


class TestPreemptionClassification:
    """elastic_fit exit-code semantics (ISSUE 15): PREEMPTED_EXIT_CODE
    restarts immediately — no backoff sleep, no crash-loop budget, no
    max_restarts consumption — while real crashes keep the old
    discipline. All deterministic via spawn_fn/clock stubs."""

    @staticmethod
    def _clock_sleep():
        t = [0.0]
        slept = []

        def clock():
            return t[0]

        def sleep(dt):
            slept.append(dt)
            t[0] += dt

        return t, slept, clock, sleep

    def test_preemption_restarts_without_backoff(self, tmp_path):
        rcs = iter([PREEMPTED_EXIT_CODE, 0])
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=0,
            spawn_fn=lambda: next(rcs), sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        assert result["ok"]
        assert result["preemptions"] == 1
        assert result["restarts"] == 0  # no crash budget consumed
        assert slept == []              # immediate restart
        kinds = [e["event"] for e in result["events"]]
        assert kinds == ["preempted", "completed"]

    def test_preemptions_do_not_trip_crash_loop(self, tmp_path):
        rcs = iter([PREEMPTED_EXIT_CODE] * 5 + [0])
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=2,
            crash_loop_window=600.0, crash_loop_budget=2,
            spawn_fn=lambda: next(rcs), sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        # 5 back-to-back preemptions inside the window: still completes
        assert result["ok"] and result["preemptions"] == 5
        assert result["restarts"] == 0 and slept == []

    def test_crash_semantics_unchanged_next_to_preemptions(self, tmp_path):
        rcs = iter([PREEMPTED_EXIT_CODE, 1, 1, 1, 1])
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=3,
            crash_loop_window=0.0,
            spawn_fn=lambda: next(rcs), sleep=sleep, clock=clock,
            log_fn=lambda m: None)
        assert not result["ok"]
        assert result["events"][-1]["event"] == "gave_up"
        assert result["restarts"] == 3 and result["preemptions"] == 1
        assert len(slept) == 3  # backoffs only for the crashes

    def test_max_preemptions_bounds_eviction_storm(self, tmp_path):
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=5,
            max_preemptions=2,
            spawn_fn=lambda: PREEMPTED_EXIT_CODE,
            sleep=lambda dt: None, clock=lambda: 0.0,
            log_fn=lambda m: None)
        assert not result["ok"]
        assert result["preemptions"] == 3  # the one over budget included
        assert result["events"][-1]["event"] == "gave_up"

    def test_preempted_metric_label(self, tmp_path):
        from deeplearning4j_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        rcs = iter([PREEMPTED_EXIT_CODE, 0])
        elastic_fit("unused:train", str(tmp_path), registry=reg,
                    spawn_fn=lambda: next(rcs), sleep=lambda dt: None,
                    clock=lambda: 0.0, log_fn=lambda m: None)
        c = reg.counter("dl4j_tpu_training_elastic_events_total", "",
                        ("event",))
        assert c.labels("preempted").value == 1
        assert c.labels("completed").value == 1
        r = reg.counter("dl4j_tpu_training_restarts_total", "", ("reason",))
        # the preemption restart IS a restart — under its own reason label
        assert r.labels("preempted").value == 1


class TestPreemptionHandler:
    def _model(self):
        class FakeModel:
            iteration_count = 7
            epoch_count = 1

        return FakeModel()

    def test_signal_sets_flag_and_next_iteration_exits(self, tmp_path):
        exits = []
        saves = []

        class FakeCkpt:
            directory = str(tmp_path)

            def save_now(self, model, iteration=None, epoch=None,
                         score=float("nan")):
                saves.append((iteration, epoch))
                return True

        h = PreemptionHandler(checkpoint=FakeCkpt(),
                              exit_fn=exits.append, log_fn=lambda m: None)
        assert not h.requested
        h.iteration_done(self._model(), 7, 1, 0.5)
        assert exits == [] and saves == []  # nothing requested yet
        h._on_signal(15, None)
        assert h.requested
        h.iteration_done(self._model(), 8, 1, 0.4)
        assert saves == [(8, 1)]
        assert exits == [PREEMPTED_EXIT_CODE]
        assert os.path.exists(os.path.join(str(tmp_path), "preempted"))

    def test_install_uninstall_roundtrip(self):
        import signal as _sig

        h = PreemptionHandler(exit_fn=lambda c: None, log_fn=lambda m: None,
                              signals=(_sig.SIGUSR1,))
        prev = _sig.getsignal(_sig.SIGUSR1)
        h.install()
        assert _sig.getsignal(_sig.SIGUSR1) == h._on_signal
        h.uninstall()
        assert _sig.getsignal(_sig.SIGUSR1) == prev

    def test_stops_watchdog_before_final_save(self, tmp_path):
        order = []

        class FakeWd:
            def stop(self, timeout=5.0):
                order.append("wd_stop")

        class FakeCkpt:
            directory = str(tmp_path)

            def save_now(self, *a, **kw):
                order.append("save")
                return True

        h = PreemptionHandler(checkpoint=FakeCkpt(), watchdog=FakeWd(),
                              exit_fn=lambda c: order.append("exit"),
                              log_fn=lambda m: None)
        h._on_signal(15, None)
        h.iteration_done(self._model(), 9, 1, 0.1)
        assert order == ["wd_stop", "save", "exit"]


class TestWatchdogStopRace:
    def test_stop_joins_thread(self, tmp_path):
        wd = Watchdog(str(tmp_path), timeout=30.0, poll_interval=0.05,
                      on_stall=lambda: None)
        wd.start()
        t = wd._thread
        wd.stop()
        assert t is not None and not t.is_alive()
        assert wd._thread is None

    def test_fire_rechecks_stop(self, tmp_path):
        """The race fix: a stall check that decided to fire re-checks
        the stop event immediately before acting, so a stop() landing
        after the timeout comparison cannot hard-exit a finished fit."""
        fired = []
        wd = Watchdog(str(tmp_path), timeout=0.0, poll_interval=0.01,
                      on_stall=lambda: fired.append(True))
        wd._stop.set()   # stop() won the race between check and fire
        wd._fire()
        assert not fired
        wd._stop.clear()
        wd._fire()
        assert fired

    def test_default_stall_noop_after_stop(self, tmp_path):
        # _default_stall would os._exit: with stop set it must return
        # (reaching os._exit here would kill the pytest process)
        wd = Watchdog(str(tmp_path), timeout=0.1)
        wd._stop.set()
        wd._default_stall()
        assert not os.path.exists(os.path.join(str(tmp_path), "stalled"))

    def test_stop_from_on_stall_thread_does_not_deadlock(self, tmp_path):
        done = threading.Event()

        def stall():
            wd.stop()  # stop() from the checker thread itself
            done.set()

        wd = Watchdog(str(tmp_path), timeout=0.0, poll_interval=0.01,
                      on_stall=stall)
        wd.start()
        assert done.wait(timeout=5.0)


def _tiny_model():
    from deeplearning4j_tpu.nn import (
        Activation, InputType, LossFunction, NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.sequential import MultiLayerNetwork
    from deeplearning4j_tpu.train.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_out=6, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _tiny_data(n=16):
    rng = np.random.RandomState(0)
    x = rng.rand(n, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, n)]
    return x, y


class TestAsyncCheckpointListener:
    def _reg(self):
        from deeplearning4j_tpu.obs.metrics import MetricsRegistry

        return MetricsRegistry()

    def test_async_artifact_matches_sync(self, tmp_path):
        from deeplearning4j_tpu.model.serializer import restore_model
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener

        x, y = _tiny_data()
        m = _tiny_model()
        d_sync, d_async = str(tmp_path / "s"), str(tmp_path / "a")
        cs = CheckpointListener(d_sync, save_every_n_iterations=1,
                                registry=self._reg())
        ca = CheckpointListener(d_async, save_every_n_iterations=1,
                                async_save=True, registry=self._reg())
        m.add_listeners(cs, ca)
        m.fit(x, y, epochs=3)
        ca.close()
        p_s = CheckpointListener.last_checkpoint(d_sync)
        p_a = CheckpointListener.last_checkpoint(d_async)
        r_s = restore_model(p_s, load_updater=True)
        r_a = restore_model(p_a, load_updater=True)
        for ln in r_s.params:
            for pn in r_s.params[ln]:
                np.testing.assert_array_equal(
                    np.asarray(r_s.params[ln][pn]),
                    np.asarray(r_a.params[ln][pn]))
        st_s = CheckpointListener.last_checkpoint_state(d_sync)
        st_a = CheckpointListener.last_checkpoint_state(d_async)
        assert st_s["iteration"] == st_a["iteration"] == 3
        assert st_s["rng"] == st_a["rng"]

    def test_bounded_queue_supersedes_oldest(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener

        ck = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                async_save=True, max_pending_writes=2,
                                registry=self._reg())
        # hold the writer hostage by filling the queue before it starts:
        # enqueue without a started writer is impossible (started on
        # first enqueue), so block it with a slow first job instead
        ev = threading.Event()

        class SlowSnap:
            class_name = "MultiLayerNetwork"

            @property
            def conf(self):
                ev.wait(5.0)
                raise RuntimeError("slow job done")

            params = {}
            state = {}
            _trainer = None

        for i in range(5):
            ck._enqueue({"model": SlowSnap(), "iteration": i, "epoch": 0,
                         "sidecar": {}})
        with ck._q_cond:
            pending = len(ck._q)
        assert pending <= 2
        ev.set()
        ck.close()
        # all the "writes" failed (RuntimeError) but nothing raised and
        # the failure counter moved — the keep-training contract
        assert ck._c_failures.value >= 1

    def test_write_fault_keeps_training_and_counts(self, tmp_path):
        from deeplearning4j_tpu.core.resilience import (
            FaultInjector, set_fault_injector)
        from deeplearning4j_tpu.train.checkpoint import (
            CHECKPOINT_WRITE_SITE, CheckpointListener)

        x, y = _tiny_data()
        m = _tiny_model()
        reg = self._reg()
        ck = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                registry=reg)
        m.add_listeners(ck)
        inj = FaultInjector()
        inj.inject_error(CHECKPOINT_WRITE_SITE,
                         lambda: OSError("disk full"), times=2)
        prev = set_fault_injector(inj)
        try:
            with pytest.warns(UserWarning, match="checkpoint save failed"):
                m.fit(x, y, epochs=2)  # both saves fail, fit survives
            m.fit(x, y, epochs=1)      # injection exhausted: save lands
        finally:
            set_fault_injector(prev)
        assert reg.counter(
            "dl4j_tpu_training_checkpoint_failures_total", "").value == 2
        assert CheckpointListener.last_checkpoint_state(
            str(tmp_path))["iteration"] == 3

    def test_pointer_only_moves_forward(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener

        x, y = _tiny_data()
        m = _tiny_model()
        ck = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                registry=self._reg())
        m.add_listeners(ck)
        m.fit(x, y, epochs=2)
        newest = ck._snapshot(m, 2, 0)
        stale = ck._snapshot(m, 1, 0)
        assert ck._write(newest, "sync")
        assert ck._write(stale, "sync")  # writes the zip, not the pointer
        st = CheckpointListener.last_checkpoint_state(str(tmp_path))
        assert st["iteration"] == 2

    def test_keep_last_prunes_pre_restart_files(self, tmp_path):
        """ISSUE 15 satellite: a fresh listener (a restarted run) must
        enumerate existing checkpoints so keep_last holds ACROSS restart
        cycles instead of growing the directory unboundedly."""
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener

        x, y = _tiny_data()
        m = _tiny_model()
        ck1 = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                 keep_last=3, registry=self._reg())
        m.add_listeners(ck1)
        m.fit(x, y, epochs=3)
        assert len([f for f in os.listdir(tmp_path)
                    if f.endswith(".zip")]) == 3
        # "restart": new listener, same dir
        m2 = _tiny_model()
        m2.iteration_count = 3
        ck2 = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                 keep_last=3, registry=self._reg())
        m2.add_listeners(ck2)
        m2.fit(x, y, epochs=2)
        zips = sorted(f for f in os.listdir(tmp_path) if f.endswith(".zip"))
        assert len(zips) == 3, zips
        assert "checkpoint_iter1_epoch0.zip" not in zips
        # sidecars pruned alongside
        states = [f for f in os.listdir(tmp_path)
                  if f.endswith(".state.json")]
        assert len(states) == 3

    def test_triggers_decoupled_and_iteration_zero_skipped(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener

        saved = []
        ck = CheckpointListener(str(tmp_path), save_every_n_iterations=4,
                                save_every_n_seconds=0.05,
                                registry=self._reg())
        ck._save = lambda model, it, ep, score=float("nan"): saved.append(it)
        m = object()
        ck.iteration_done(m, 0, 0, 0.1)      # iteration 0 never saves
        assert saved == []
        ck.iteration_done(m, 4, 0, 0.1)      # iteration trigger
        assert saved == [4]
        ck._last_save_time = time.time() - 1.0
        ck.iteration_done(m, 5, 0, 0.1)      # TIME trigger despite 5 % 4
        assert saved == [4, 5]
        ck._last_save_time = time.time()
        ck.iteration_done(m, 6, 0, 0.1)      # neither trigger due
        assert saved == [4, 5]

    def test_prune_never_evicts_pointer_target(self, tmp_path):
        """Regression (found driving the preemption path): keep_last
        pruning evicted in COMPLETION order, so a forced final sync save
        landing before stale async stragglers was deleted — the pointer
        then named a missing file. Eviction must follow (epoch,
        iteration) order and spare the pointer target."""
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener

        x, y = _tiny_data()
        m = _tiny_model()
        ck = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                                keep_last=2, registry=self._reg())
        m.add_listeners(ck)
        m.fit(x, y, epochs=2)
        # forced final save first, then stale writes complete after it
        newest = ck._snapshot(m, 9, 3)
        assert ck._write(newest, "sync")
        for it in (5, 6, 7):
            assert ck._write(ck._snapshot(m, it, 2), "async")
        path = CheckpointListener.last_checkpoint(str(tmp_path))
        assert path is not None and path.endswith("iter9_epoch3.zip")
        zips = sorted(f for f in os.listdir(tmp_path) if f.endswith(".zip"))
        assert len(zips) == 2 and "checkpoint_iter9_epoch3.zip" in zips

    def test_save_now_is_sync_and_durable(self, tmp_path):
        from deeplearning4j_tpu.train.checkpoint import CheckpointListener

        x, y = _tiny_data()
        m = _tiny_model()
        ck = CheckpointListener(str(tmp_path), save_every_n_iterations=100,
                                async_save=True, registry=self._reg())
        m.add_listeners(ck)
        m.fit(x, y, epochs=1)
        assert CheckpointListener.last_checkpoint(str(tmp_path)) is None
        assert ck.save_now(m)
        st = CheckpointListener.last_checkpoint_state(str(tmp_path))
        assert st["iteration"] == m.iteration_count
        ck.close()


def test_watchdog_ignores_stale_heartbeat_on_restart(tmp_path):
    """Regression: a restarted child inherits the previous run's old
    heartbeat file — it must still get the full grace period."""
    hb = HeartbeatListener(str(tmp_path))
    hb.iteration_done(None, 5, 0, 0.1)
    # age the heartbeat far past the timeout
    path = os.path.join(str(tmp_path), "heartbeat.json")
    import json as _json
    with open(path) as f:
        data = _json.load(f)
    data["ts"] -= 100.0
    with open(path, "w") as f:
        _json.dump(data, f)

    fired = []
    wd = Watchdog(str(tmp_path), timeout=0.6, poll_interval=0.05,
                  on_stall=lambda: fired.append(True))
    wd.start()
    time.sleep(0.3)
    assert not fired  # grace period counted from start(), not the stale ts
    time.sleep(0.6)
    wd.stop()
    assert fired  # and it still fires once the REAL grace period lapses


class TestElasticResize:
    """ISSUE 16: mesh_size_fn width resolution, reason-labeled restarts,
    reshard events, and the supervisor plumbing that carries the width to
    the child. Deterministic — spawn_fn stubs, fake clock."""

    @staticmethod
    def _clock_sleep():
        t = [0.0]
        slept = []

        def clock():
            return t[0]

        def sleep(dt):
            slept.append(dt)
            t[0] += dt

        return t, slept, clock, sleep

    def test_width_reaches_spawn_fn_and_resize_is_labeled(self, tmp_path):
        from deeplearning4j_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        widths = iter([8, 4])
        rcs = iter([1, 0])
        seen = []

        def spawn(mesh_size):
            seen.append(mesh_size)
            return next(rcs)

        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=3,
            spawn_fn=spawn, sleep=sleep, clock=clock,
            mesh_size_fn=lambda: next(widths),
            registry=reg, log_fn=lambda m: None)
        assert result["ok"] and seen == [8, 4]
        kinds = [e["event"] for e in result["events"]]
        assert kinds == ["crash", "backoff", "reshard", "completed"]
        resh = next(e for e in result["events"] if e["event"] == "reshard")
        assert resh["from_width"] == 8 and resh["to_width"] == 4
        r = reg.counter("dl4j_tpu_training_restarts_total", "", ("reason",))
        assert r.labels("resize").value == 1
        assert r.labels("crash").value == 0

    def test_same_width_restart_keeps_failure_reason(self, tmp_path):
        from deeplearning4j_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        rcs = iter([1, 86, 0])
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=5,
            spawn_fn=lambda w: next(rcs), sleep=sleep, clock=clock,
            mesh_size_fn=lambda: 8,
            registry=reg, log_fn=lambda m: None)
        assert result["ok"]
        kinds = [e["event"] for e in result["events"]]
        assert kinds == ["crash", "backoff", "stall", "backoff", "completed"]
        r = reg.counter("dl4j_tpu_training_restarts_total", "", ("reason",))
        assert r.labels("crash").value == 1
        assert r.labels("stall").value == 1
        assert r.labels("resize").value == 0

    def test_legacy_zero_arg_spawn_fn_still_works(self, tmp_path):
        rcs = iter([1, 0])
        _, slept, clock, sleep = self._clock_sleep()
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=2,
            spawn_fn=lambda: next(rcs), sleep=sleep, clock=clock,
            mesh_size_fn=lambda: 4, log_fn=lambda m: None)
        assert result["ok"] and result["restarts"] == 1

    def test_mesh_child_env_rewrites_cpu_device_count(self):
        from deeplearning4j_tpu.train.fault_tolerance import _mesh_child_env

        env = {"JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=8 "
                            "--xla_dump_to=/tmp/d"}
        out = _mesh_child_env(env, 4)
        assert out["DL4J_ELASTIC_MESH_SIZE"] == "4"
        assert "--xla_force_host_platform_device_count=4" in out["XLA_FLAGS"]
        assert "device_count=8" not in out["XLA_FLAGS"]
        assert "--xla_dump_to=/tmp/d" in out["XLA_FLAGS"]  # preserved
        # no width -> env untouched
        assert "DL4J_ELASTIC_MESH_SIZE" not in _mesh_child_env(env, None)

    def test_mesh_child_env_leaves_tpu_platform_flags_alone(self):
        from deeplearning4j_tpu.train.fault_tolerance import _mesh_child_env

        env = {"JAX_PLATFORMS": "tpu", "XLA_FLAGS": "--xla_foo=1"}
        out = _mesh_child_env(env, 16)
        # advisory env var only: a real fleet's device count is the
        # scheduler's business, not a host-platform flag
        assert out["DL4J_ELASTIC_MESH_SIZE"] == "16"
        assert out["XLA_FLAGS"] == "--xla_foo=1"

    def test_accepts_mesh_size_arities(self):
        from deeplearning4j_tpu.train.fault_tolerance import _accepts_mesh_size

        assert _accepts_mesh_size(lambda a, b, mesh_size=None: None)
        assert _accepts_mesh_size(lambda a, b, c: None)
        assert _accepts_mesh_size(lambda *args: None)
        assert not _accepts_mesh_size(lambda a, b: None)


class TestGoodputLedger:
    """ISSUE 16: the supervisor's downtime itemization and goodput ratio,
    deterministic via fake clock/sleep (no heartbeat files -> the
    boot-time and heartbeat-age terms are absent by construction)."""

    def test_result_carries_ledger_and_backoff_downtime(self, tmp_path):
        from deeplearning4j_tpu.core.resilience import RetryPolicy
        from deeplearning4j_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        t = [0.0]
        slept = []

        def clock():
            return t[0]

        def sleep(dt):
            slept.append(dt)
            t[0] += dt

        rcs = iter([1, 1, 0])
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=5,
            retry_policy=RetryPolicy(max_retries=5, initial_backoff=1.0,
                                     multiplier=2.0, jitter=0.0),
            spawn_fn=lambda: next(rcs), sleep=sleep, clock=clock,
            registry=reg, log_fn=lambda m: None)
        assert result["ok"]
        gp = result["goodput"]
        # the fake clock only advances inside sleep(): wall == backoff
        # downtime, so every second was downtime and the ratio is 0
        assert gp["downtime_seconds"]["backoff"] == sum(slept) == 3.0
        assert gp["wall_seconds"] == 3.0
        assert gp["useful_seconds"] == 0.0 and gp["ratio"] == 0.0
        c = reg.counter("dl4j_tpu_training_downtime_seconds_total", "",
                        ("reason",))
        assert c.labels("backoff").value == 3.0
        g = reg.gauge("dl4j_tpu_training_goodput_ratio", "")
        assert g.value == 0.0

    def test_clean_run_has_full_goodput(self, tmp_path):
        t = [0.0]

        def clock():
            t[0] += 5.0  # every clock() read advances: wall > 0
            return t[0]

        result = elastic_fit(
            "unused:train", str(tmp_path), spawn_fn=lambda: 0,
            sleep=lambda dt: None, clock=clock, log_fn=lambda m: None)
        gp = result["goodput"]
        assert gp["ratio"] == 1.0
        assert gp["useful_seconds"] == gp["wall_seconds"] > 0
        assert all(v == 0.0 for v in gp["downtime_seconds"].values())

    def test_stall_downtime_uses_heartbeat_age(self, tmp_path):
        import json as _json

        # a heartbeat 5 "wall" seconds stale at failure time
        with open(os.path.join(str(tmp_path), "heartbeat.json"), "w") as f:
            _json.dump({"iteration": 3, "ts": time.time() - 5.0}, f)
        rcs = iter([86, 0])
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=2,
            stall_timeout=300.0,
            spawn_fn=lambda: next(rcs), sleep=lambda dt: None,
            clock=lambda: 0.0, log_fn=lambda m: None)
        assert result["ok"]
        stall_ev = result["events"][0]
        assert stall_ev["event"] == "stall"
        assert stall_ev["heartbeat_age_s"] == pytest.approx(5.0, abs=1.0)
        # the itemized stall seconds are the measured age, NOT the
        # configured 300s timeout
        assert result["goodput"]["downtime_seconds"]["stall"] == \
            pytest.approx(5.0, abs=1.0)

    def test_stall_without_heartbeat_charges_full_timeout(self, tmp_path):
        rcs = iter([86, 0])
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=2,
            stall_timeout=42.0,
            spawn_fn=lambda: next(rcs), sleep=lambda dt: None,
            clock=lambda: 0.0, log_fn=lambda m: None)
        assert result["events"][0]["heartbeat_age_s"] is None
        assert result["goodput"]["downtime_seconds"]["stall"] == 42.0


class TestHeartbeatHardening:
    """ISSUE 16 satellites: crash-consistent heartbeat writes and a
    read path that tolerates torn/empty/garbage files."""

    def test_read_heartbeat_tolerates_missing_empty_torn(self, tmp_path):
        d = str(tmp_path)
        assert read_heartbeat(d) is None  # missing
        path = os.path.join(d, "heartbeat.json")
        open(path, "w").close()
        assert read_heartbeat(d) is None  # empty
        with open(path, "w") as f:
            f.write('{"iteration": 3, "ts"')  # torn mid-write
        assert read_heartbeat(d) is None
        with open(path, "w") as f:
            f.write("[1, 2, 3]")  # parseable but not a beat
        assert read_heartbeat(d) is None

    def test_heartbeat_write_is_atomic_and_keeps_first_ts(self, tmp_path):
        import glob

        hb = HeartbeatListener(str(tmp_path))
        hb.iteration_done(None, 1, 0, 0.5)
        first = read_heartbeat(str(tmp_path))
        time.sleep(0.02)
        hb.iteration_done(None, 2, 0, 0.4)
        second = read_heartbeat(str(tmp_path))
        assert second["iteration"] == 2
        assert second["pid"] == os.getpid()
        # first_ts survives across beats (boot-time pricing anchor) while
        # ts advances
        assert second["first_ts"] == first["first_ts"] == first["ts"]
        assert second["ts"] > first["ts"]
        # tmp + os.replace discipline leaves no debris behind
        assert glob.glob(os.path.join(str(tmp_path), "*.tmp*")) == []
        assert glob.glob(os.path.join(str(tmp_path), ".tmp*")) == []

    def test_watchdog_tolerates_ts_less_heartbeat(self, tmp_path):
        import json as _json

        with open(os.path.join(str(tmp_path), "heartbeat.json"), "w") as f:
            _json.dump({"iteration": 1}, f)  # dict, but no ts field
        fired = []
        wd = Watchdog(str(tmp_path), timeout=0.2, poll_interval=0.05,
                      on_stall=lambda: fired.append(True))
        wd.start()
        time.sleep(0.5)
        wd.stop()
        assert fired  # treated as "no beat yet", aged from start()

    def test_crash_event_heartbeat_age(self, tmp_path):
        import json as _json

        with open(os.path.join(str(tmp_path), "heartbeat.json"), "w") as f:
            _json.dump({"iteration": 9, "ts": time.time() - 7.0}, f)
        rcs = iter([1])
        result = elastic_fit(
            "unused:train", str(tmp_path), max_restarts=0,
            spawn_fn=lambda: next(rcs), sleep=lambda dt: None,
            clock=lambda: 0.0, log_fn=lambda m: None)
        ev = result["events"][0]
        assert ev["event"] == "crash"
        # died-mid-step vs stale-since-boot is now readable off the event
        assert ev["heartbeat_age_s"] == pytest.approx(7.0, abs=1.0)
        assert result["goodput"]["downtime_seconds"]["crash"] == \
            pytest.approx(7.0, abs=1.0)
