"""Model zoo smoke tests: every architecture builds, runs a tiny forward
with the expected output shape, and (for the flagship families) takes a
training step (SURVEY.md §2.2 "Model zoo")."""

import numpy as np
import pytest

from deeplearning4j_tpu.model.zoo import (
    AlexNet,
    Darknet19,
    InceptionResNetV1,
    LeNet,
    SqueezeNet,
    TextGenerationLSTM,
    TinyYOLO,
    UNet,
    VGG16,
    VGG19,
    Xception,
)


def _x(b, c, h, w, seed=0):
    return np.random.RandomState(seed).rand(b, c, h, w).astype(np.float32)


def test_alexnet_small_forward():
    m = AlexNet(num_classes=5, height=96, width=96).init()
    out = m.output(_x(2, 3, 96, 96))
    assert out.shape == (2, 5)
    assert np.allclose(np.asarray(out).sum(1), 1, atol=1e-4)


def test_vgg19_builds():
    m = VGG19(num_classes=4, height=64, width=64).init()
    out = m.output(_x(1, 3, 64, 64))
    assert out.shape == (1, 4)
    # VGG19 has 3 more convs than VGG16
    n16 = sum(1 for l in VGG16(num_classes=4, height=64, width=64)
              .conf().layers if type(l).__name__ == "ConvolutionLayer")
    n19 = sum(1 for l in VGG19(num_classes=4, height=64, width=64)
              .conf().layers if type(l).__name__ == "ConvolutionLayer")
    assert (n16, n19) == (13, 16)


def test_squeezenet_forward_and_fit():
    m = SqueezeNet(num_classes=6, height=64, width=64).init()
    out = m.output(_x(2, 3, 64, 64))
    assert out.shape == (2, 6)
    assert np.allclose(np.asarray(out).sum(1), 1, atol=1e-4)
    y = np.eye(6, dtype=np.float32)[[0, 3]]
    s0 = m.score([_x(2, 3, 64, 64)], [y])
    m.fit([_x(2, 3, 64, 64)], [y], epochs=3)
    assert m.score([_x(2, 3, 64, 64)], [y]) < s0


def test_darknet19_forward():
    m = Darknet19(num_classes=7, height=64, width=64).init()
    out = m.output(_x(1, 3, 64, 64))
    assert out.shape == (1, 7)


def test_tiny_yolo_grid_shape():
    m = TinyYOLO(num_classes=3, num_boxes=5, height=128, width=128).init()
    out = m.output(_x(1, 3, 128, 128))
    # 128 / 2^5 = 4 grid, depth = 5 * (5 + 3)
    assert out.shape == (1, 5 * 8, 4, 4)


def test_unet_shapes_match_input():
    m = UNet(num_classes=2, height=32, width=32, base_filters=8,
             depth=2).init()
    out = m.output(_x(1, 3, 32, 32))
    assert out.shape == (1, 2, 32, 32)
    vals = np.asarray(out)
    assert ((vals >= 0) & (vals <= 1)).all()  # sigmoid head


def test_xception_forward():
    m = Xception(num_classes=4, height=64, width=64, middle_blocks=1).init()
    out = m.output(_x(1, 3, 64, 64))
    assert out.shape == (1, 4)
    assert np.allclose(np.asarray(out).sum(1), 1, atol=1e-4)


def test_inception_resnet_v1_forward():
    m = InceptionResNetV1(num_classes=4, height=96, width=96, blocks_a=1,
                          blocks_b=1, blocks_c=1).init()
    out = m.output(_x(1, 3, 96, 96))
    assert out.shape == (1, 4)
    assert np.allclose(np.asarray(out).sum(1), 1, atol=1e-4)


def test_textgen_lstm_trains():
    vocab = 10
    m = TextGenerationLSTM(vocab_size=vocab, hidden=16, layers=2,
                           tbptt_length=8).init()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (4, 20))
    x = np.eye(vocab, dtype=np.float32)[ids].transpose(0, 2, 1)  # [b,v,t]
    # next-char labels: shift by one
    y = np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, 1)].transpose(0, 2, 1)
    out = m.output(x)
    assert out.shape == (4, vocab, 20)
    s0 = m.score(x, y)
    m.fit(x, y, epochs=5)
    assert m.score(x, y) < s0


def test_simple_cnn_trains():
    from deeplearning4j_tpu.model.zoo import SimpleCNN

    m = SimpleCNN(num_classes=4, height=16, width=16, seed=5).init()
    rng = np.random.RandomState(0)
    x = rng.rand(8, 3, 16, 16).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 8)]
    losses = []
    for _ in range(8):
        m.fit(x, y, epochs=1)
        losses.append(m.score_value)
    assert np.isfinite(losses[-1]) and losses[-1] < losses[0]


def test_yolo2_grid_shape_and_passthrough():
    from deeplearning4j_tpu.model.zoo import YOLO2

    y = YOLO2(num_classes=3, n_boxes=5, height=64, width=64, seed=6).init()
    out = np.asarray(y.output(
        np.random.RandomState(1).rand(2, 3, 64, 64).astype(np.float32)))
    # 64 / 32 = 2x2 grid; B*(5+C) = 5*8 = 40 channels
    assert out.shape == (2, 40, 2, 2)
    # the reorg passthrough really feeds the head: concat vertex exists
    names = [s.name for s in y.conf.vertices]
    assert "reorg" in names and "concat" in names


def test_facenet_unit_norm_embeddings():
    from deeplearning4j_tpu.model.zoo import FaceNetNN4Small2

    f = FaceNetNN4Small2(embedding_size=64, seed=7, height=96, width=96).init()
    emb = np.asarray(f.output(
        np.random.RandomState(2).rand(3, 3, 96, 96).astype(np.float32)))
    assert emb.shape == (3, 64)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=-1), 1.0, rtol=1e-5)


def test_nasnet_forward_and_fit():
    from deeplearning4j_tpu.model.zoo import NASNet
    from deeplearning4j_tpu.train.graph_solver import GraphSolver

    m = NASNet(num_classes=4, height=32, width=32, num_blocks=1,
               penultimate_filters=120, stem_filters=8).init()
    out = m.output(_x(2, 3, 32, 32))
    assert out.shape == (2, 4)
    assert np.allclose(np.asarray(out).sum(1), 1, atol=1e-4)
    y = np.eye(4, dtype=np.float32)[np.asarray([0, 1])]
    s = GraphSolver(m)
    l0 = float(s.fit_batch((np.asarray(_x(2, 3, 32, 32)),), (y,)))
    l1 = l0
    for _ in range(5):
        l1 = float(s.fit_batch((np.asarray(_x(2, 3, 32, 32)),), (y,)))
    assert np.isfinite(l1) and l1 < l0
