"""Sequence-parallel attention (ring + Ulysses) vs single-device reference
on the 8-virtual-device CPU mesh (SURVEY.md §4 multi-node-without-a-cluster
test strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import mha_attention_reference
from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.sequence import (
    ring_attention,
    ulysses_attention,
)


@pytest.fixture(scope="module")
def seq_mesh():
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    return make_mesh(seq=4, devices=jax.devices()[:4])


def _rand(key, *shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(seq_mesh, causal):
    q = _rand(0, 2, 4, 32, 8)
    k = _rand(1, 2, 4, 32, 8)
    v = _rand(2, 2, 4, 32, 8)
    ref = mha_attention_reference(q, k, v, causal=causal)
    out = ring_attention(q, k, v, causal=causal, mesh=seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_with_mask(seq_mesh):
    q = _rand(0, 2, 2, 32, 8)
    k = _rand(1, 2, 2, 32, 8)
    v = _rand(2, 2, 2, 32, 8)
    mask = jnp.asarray(np.random.RandomState(0).rand(2, 32) > 0.3,
                       jnp.float32)
    ref = mha_attention_reference(q, k, v, mask=mask)
    out = ring_attention(q, k, v, mask=mask, mesh=seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_grads(seq_mesh):
    q = _rand(0, 1, 2, 16, 8)
    k = _rand(1, 1, 2, 16, 8)
    v = _rand(2, 1, 2, 16, 8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=True,
                                      mesh=seq_mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_attention_reference(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(seq_mesh, causal):
    q = _rand(0, 2, 4, 32, 8)  # 4 heads over 4 devices
    k = _rand(1, 2, 4, 32, 8)
    v = _rand(2, 2, 4, 32, 8)
    ref = mha_attention_reference(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, causal=causal, mesh=seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_with_mask(seq_mesh):
    q = _rand(0, 2, 4, 32, 8)
    k = _rand(1, 2, 4, 32, 8)
    v = _rand(2, 2, 4, 32, 8)
    mask = jnp.asarray(np.random.RandomState(1).rand(2, 32) > 0.3,
                       jnp.float32)
    ref = mha_attention_reference(q, k, v, mask=mask)
    out = ulysses_attention(q, k, v, mask=mask, mesh=seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_jits_in_train_step(seq_mesh):
    """Ring attention inside a jitted loss+grad step (the way a training
    loop consumes it)."""
    q = _rand(0, 1, 2, 16, 8)

    @jax.jit
    def step(q):
        return jnp.sum(ring_attention(q, q, q, causal=True, mesh=seq_mesh))

    assert np.isfinite(float(step(q)))


def test_divisibility_errors(seq_mesh):
    q = _rand(0, 1, 2, 30, 8)
    with pytest.raises(ValueError):
        ring_attention(q, q, q, mesh=seq_mesh)
    q2 = _rand(0, 1, 3, 32, 8)  # 3 heads not divisible by 4
    with pytest.raises(ValueError):
        ulysses_attention(q2, q2, q2, mesh=seq_mesh)


def test_ring_attention_causal_cross_length(seq_mesh):
    """tq != tk causal alignment (end-aligned, matching the reference)."""
    q = _rand(0, 1, 2, 16, 8)
    k = _rand(1, 1, 2, 32, 8)
    v = _rand(2, 1, 2, 32, 8)
    ref = mha_attention_reference(q, k, v, causal=True)
    out = ring_attention(q, k, v, causal=True, mesh=seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradient_parity(seq_mesh, causal):
    """Long-context TRAINING: gradients through the ring (checkpointed
    scan + ppermute collectives) must match the dense reference."""
    q = _rand(30, 1, 2, 32, 8)
    k = _rand(31, 1, 2, 32, 8)
    v = _rand(32, 1, 2, 32, 8)

    def loss_ring(a, b, c):
        return jnp.sum(jnp.square(ring_attention(a, b, c, causal=causal,
                                                 mesh=seq_mesh)))

    def loss_ref(a, b, c):
        return jnp.sum(jnp.square(mha_attention_reference(a, b, c,
                                                          causal=causal)))

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gd, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   atol=1e-4, rtol=1e-4, err_msg=f"d{name}")
