"""ModelRouter: deterministic hash splitting + shadow mirroring
(serving/router.py), unit-tested against fake backends."""

from concurrent.futures import Future

import numpy as np
import pytest

from deeplearning4j_tpu.obs import MetricsRegistry
from deeplearning4j_tpu.serving import ModelRouter


class FakeBackend:
    def __init__(self, version, result=0.0, fail=False):
        self.model_version = str(version)
        self.result = result
        self.fail = fail
        self.calls = []

    def output_async(self, x, *, timeout=None, deadline=None):
        self.calls.append(np.asarray(x))
        fut = Future()
        if self.fail:
            fut.set_exception(RuntimeError("backend down"))
        else:
            fut.set_result(np.full((1,), self.result))
        return fut


def test_weight_zero_routes_everything_primary():
    p, c = FakeBackend(1), FakeBackend(2)
    r = ModelRouter(p, canary=c, canary_weight=0.0,
                    registry=MetricsRegistry())
    for i in range(20):
        assert r.assign(np.zeros(2), key=f"k{i}") == "primary"


def test_weight_one_routes_everything_canary():
    p, c = FakeBackend(1), FakeBackend(2)
    r = ModelRouter(p, canary=c, canary_weight=1.0,
                    registry=MetricsRegistry())
    for i in range(20):
        assert r.assign(np.zeros(2), key=f"k{i}") == "canary"


def test_assignment_is_deterministic_per_key():
    p, c = FakeBackend(1), FakeBackend(2)
    r = ModelRouter(p, canary=c, canary_weight=0.3, salt="s",
                    registry=MetricsRegistry())
    first = {f"k{i}": r.assign(np.zeros(2), key=f"k{i}") for i in range(50)}
    for k, want in first.items():
        assert r.assign(np.ones(2), key=k) == want  # payload irrelevant
    # a different salt reshuffles the split
    r2 = ModelRouter(p, canary=c, canary_weight=0.3, salt="other",
                     registry=MetricsRegistry())
    assert any(r2.assign(np.zeros(2), key=k) != v for k, v in first.items())


def test_keyless_requests_hash_payload():
    p, c = FakeBackend(1), FakeBackend(2)
    r = ModelRouter(p, canary=c, canary_weight=0.5,
                    registry=MetricsRegistry())
    x = np.arange(8, dtype=np.float32)
    assert len({r.assign(x) for _ in range(5)}) == 1  # stable per payload


def test_split_fraction_tracks_weight():
    p, c = FakeBackend(1), FakeBackend(2)
    r = ModelRouter(p, canary=c, canary_weight=0.25,
                    registry=MetricsRegistry())
    hits = sum(r.assign(np.zeros(2), key=f"user-{i}") == "canary"
               for i in range(2000))
    assert 0.18 < hits / 2000 < 0.32


def test_submit_returns_owning_version_and_counts():
    reg = MetricsRegistry()
    p, c = FakeBackend(1, result=1.0), FakeBackend(2, result=2.0)
    r = ModelRouter(p, canary=c, canary_weight=0.5, name="m", registry=reg)
    seen = {"1": 0, "2": 0}
    for i in range(40):
        fut, target, version = r.submit(np.zeros(2), key=f"u{i}")
        out = fut.result()
        assert out[0] == float(version)  # response came from that backend
        assert (target == "canary") == (version == "2")
        seen[version] += 1
    assert seen["1"] > 0 and seen["2"] > 0
    fam = reg.get("dl4j_tpu_serving_routes_total")
    assert fam.labels("m", "primary").value == seen["1"]
    assert fam.labels("m", "canary").value == seen["2"]


def test_shadow_mirrors_every_request_fail_open():
    reg = MetricsRegistry()
    p = FakeBackend(1, result=1.0)
    sh = FakeBackend(9, fail=True)  # shadow is broken — must not matter
    r = ModelRouter(p, shadow=sh, name="m", registry=reg)
    for _ in range(10):
        fut, target, version = r.submit(np.zeros(2))
        assert fut.result()[0] == 1.0 and target == "primary"
    assert len(sh.calls) == 10
    fam = reg.get("dl4j_tpu_serving_routes_total")
    assert fam.labels("m", "shadow").value == 10


def test_shadow_sync_raise_is_swallowed():
    class Exploding(FakeBackend):
        def output_async(self, x, **kw):
            raise RuntimeError("admission rejected")

    p = FakeBackend(1, result=1.0)
    r = ModelRouter(p, shadow=Exploding(9), registry=MetricsRegistry())
    fut, _, _ = r.submit(np.zeros(2))
    assert fut.result()[0] == 1.0


def test_shadow_receives_a_copy_not_the_live_buffer():
    p, sh = FakeBackend(1), FakeBackend(2)
    r = ModelRouter(p, shadow=sh, registry=MetricsRegistry())
    x = np.zeros(4, np.float32)
    r.submit(x)
    x += 99.0  # caller mutates after submit
    assert sh.calls[0][0] == 0.0  # the mirror saw the original values


def test_invalid_weights_rejected():
    p, c = FakeBackend(1), FakeBackend(2)
    with pytest.raises(ValueError):
        ModelRouter(p, canary=c, canary_weight=1.5,
                    registry=MetricsRegistry())
    with pytest.raises(ValueError):
        ModelRouter(p, canary_weight=0.5, registry=MetricsRegistry())
