"""Test configuration.

Tests run on CPU with 8 virtual devices so sharding/mesh tests exercise real
multi-device paths without TPU hardware (SURVEY.md §4 "distributed without a
cluster"). The real-TPU path is exercised by bench.py / __graft_entry__.py.

This must run before jax initializes its backends, hence env vars set at
import time (conftest imports before test modules).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the session's axon (TPU) default
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The session's sitecustomize imports jax (axon PJRT registration) before
# conftest runs, so JAX_PLATFORMS was already latched — update config directly.
jax.config.update("jax_platforms", "cpu")
# float64 enabled globally: gradient checks require double precision
# (reference: DataType.DOUBLE for GradCheckUtil); float32 paths pass explicit
# dtypes everywhere so this does not change their behavior.
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture
def rng():
    from deeplearning4j_tpu.core import RngState

    return RngState(12345)


@pytest.fixture(autouse=True)
def _reset_environment():
    yield
    from deeplearning4j_tpu.core import get_environment

    get_environment().reset()
