"""bench.py ``--rows`` selector smoke (ISSUE 13 satellite): a single
extras row — e.g. ``quantized_infer_speedup`` — must be runnable
standalone in CI, the selector must filter exactly, and a typo'd row
name must fail loudly (exit 2) instead of silently benching nothing.
No measurement actually runs here: the selection layer is pure."""

import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location("_bench_under_test",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # imports stdlib only at module level
    return mod


def test_select_rows_filters_exactly():
    bench = _load_bench()
    sel = bench.select_rows("quantized_infer_speedup")
    assert sel == {"quantized_infer_speedup": "quantized_infer"}
    sel = bench.select_rows(" int8_kv_cache , lenet_smoke ")
    assert list(sel) == ["int8_kv_cache", "lenet_smoke"]
    assert sel["int8_kv_cache"] == "int8_kv_cache"
    # ISSUE 14: the large-batch row is a standalone CI entry point
    sel = bench.select_rows("large_batch_scaling")
    assert sel == {"large_batch_scaling": "large_batch_scaling"}
    # ISSUE 15: the checkpoint-stall row gates the async writer
    sel = bench.select_rows("checkpoint_stall")
    assert sel == {"checkpoint_stall": "checkpoint_stall"}
    # ISSUE 16: the elastic-goodput row gates the >0.90 churn ratio
    sel = bench.select_rows("elastic_goodput")
    assert sel == {"elastic_goodput": "elastic_goodput"}
    # ISSUE 17: the paged-KV and disagg rows run standalone in CI
    sel = bench.select_rows("paged_kv_occupancy,disagg_handoff")
    assert list(sel) == ["paged_kv_occupancy", "disagg_handoff"]
    assert sel["paged_kv_occupancy"] == "paged_kv_occupancy"
    assert sel["disagg_handoff"] == "disagg_handoff"
    # ISSUE 18: moe_dispatch is CPU-runnable now (grouped no-regression
    # gate runs everywhere; the ≤1.5 overhead ratio stays chip-only)
    sel = bench.select_rows("moe_dispatch")
    assert sel == {"moe_dispatch": "moe_dispatch"}
    assert "moe_dispatch" in bench._EXTRA_ROWS
    assert "moe_dispatch" not in bench._CHIP_ONLY_ROWS
    # ISSUE 19: the multiplexing row (>= 2x models-served at equal byte
    # budget) is a standalone CPU CI entry point
    sel = bench.select_rows("model_multiplex")
    assert sel == {"model_multiplex": "model_multiplex"}
    assert "model_multiplex" not in bench._CHIP_ONLY_ROWS
    # ISSUE 20: the pipeline-bubble row (<0.35 1F1B gate at S=4/M=8)
    # runs on the 8-virtual-device CPU fallback
    sel = bench.select_rows("pipeline_bubble_share")
    assert sel == {"pipeline_bubble_share": "pipeline_bubble_share"}
    assert "pipeline_bubble_share" in bench._EXTRA_ROWS
    assert "pipeline_bubble_share" not in bench._CHIP_ONLY_ROWS
    # every selectable row maps to a registered measurement
    for row, meas in {**bench._EXTRA_ROWS, **bench._CHIP_ONLY_ROWS}.items():
        assert meas in bench._MEASUREMENTS, (row, meas)


def test_moe_dispatch_row_grouped_columns():
    """The moe_dispatch row reports all three dispatch modes and the
    grouped gates (ISSUE 18) on a CPU-sized config."""
    bench = _load_bench()
    row = bench.measure_moe_dispatch(tokens=64, d=16, experts=4, top_k=2,
                                     hidden=32, iters=1)
    for key in ("moe_sort_grad_step_ms", "moe_einsum_grad_step_ms",
                "moe_grouped_grad_step_ms", "grouped_dispatch_overhead_ratio",
                "grouped_vs_sort_speedup"):
        assert isinstance(row[key], float) and row[key] > 0, key
    gate = row["grouped_no_regression_vs_sort"]
    assert set(gate) == {"max_ratio", "ratio", "ok"}
    # iters=1 on micro shapes is timing-noise territory; the structural
    # contract is the smoke here — the real gate runs via --rows with
    # the tuned cpu kwargs (see _child_measure)
    assert gate["ok"] == (gate["ratio"] <= gate["max_ratio"])
    chip = row["grouped_overhead_chip_target"]
    assert chip["chip_only"] is True and chip["max"] == 1.5


def test_select_rows_rejects_unknown_and_empty():
    import pytest

    bench = _load_bench()
    with pytest.raises(ValueError, match="bogus_row"):
        bench.select_rows("lenet_smoke,bogus_row")
    with pytest.raises(ValueError):
        bench.select_rows("  ,  ")


def test_rows_arg_parsing():
    bench = _load_bench()
    assert bench._parse_rows_arg(["--rows", "a,b"]) == "a,b"
    assert bench._parse_rows_arg(["--rows=a,b"]) == "a,b"
    assert bench._parse_rows_arg(["other"]) is None
    import pytest

    with pytest.raises(ValueError):
        bench._parse_rows_arg(["--rows"])


def test_cli_list_rows_and_unknown_row_exit():
    # --list-rows answers without importing jax or probing hardware
    out = subprocess.run([sys.executable, _BENCH, "--list-rows"],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    listing = json.loads(out.stdout.strip())
    assert "quantized_infer_speedup" in listing["rows"]
    assert "int8_kv_cache" in listing["rows"]
    assert "large_batch_scaling" in listing["rows"]
    assert "checkpoint_stall" in listing["rows"]
    assert "elastic_goodput" in listing["rows"]
    assert "paged_kv_occupancy" in listing["rows"]
    assert "disagg_handoff" in listing["rows"]
    assert "model_multiplex" in listing["rows"]
    assert "pipeline_bubble_share" in listing["rows"]
    # an unknown row fails fast (exit 2, error names the row) BEFORE any
    # probe/measurement work
    bad = subprocess.run([sys.executable, _BENCH, "--rows", "nope"],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 2
    assert "nope" in bad.stderr
